"""Gradient accumulation (BASELINE.json configs[2]/[3]: declared global
batches larger than a small mesh can hold in one activation pass).

Parity contract: for models whose loss is a mean over examples (no
BatchNorm), an accum_steps=A step equals the monolithic step exactly —
mean of per-microbatch gradient means IS the full-batch gradient mean.
Asserted at f32 with dropout off. BatchNorm models instead update their
running stats per microbatch sequentially (smaller per-microbatch
statistics) — checked for finiteness + loss descent, not bit parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.train import (
    compile_step,
    create_train_state,
    make_classification_train_step,
)
from tpudl.train.loop import microbatch


def _token_batch(rng, batch, seq_len=16, vocab=256):
    return {
        "input_ids": rng.integers(0, vocab, size=(batch, seq_len)).astype(
            np.int32
        ),
        "attention_mask": np.ones((batch, seq_len), np.int32),
        "label": rng.integers(0, 2, size=(batch,)).astype(np.int32),
    }


def _bert_state(lr=1e-3):
    from tpudl.models.bert import BertConfig, BertForSequenceClassification

    cfg = BertConfig(
        vocab_size=256,
        hidden_size=32,
        num_layers=2,
        num_heads=2,
        intermediate_size=64,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    return create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 16), jnp.int32),
        optax.adamw(lr),
    )


@pytest.mark.parametrize("accum", [2, 4])
def test_accumulated_step_matches_monolithic(mesh8, accum):
    """accum=A step == accum=1 step at f32 (params and metrics)."""
    rng_np = np.random.default_rng(0)
    batch = _token_batch(rng_np, 32)
    rng = jax.random.key(1)

    results = {}
    for a in (1, accum):
        state = _bert_state()
        step = compile_step(
            make_classification_train_step(
                input_keys=("input_ids", "attention_mask"),
                label_key="label",
                accum_steps=a,
            ),
            mesh8,
            state,
            None,
            donate_state=False,
        )
        new_state, metrics = step(state, batch, rng)
        results[a] = (new_state.params, metrics)

    p1, m1 = results[1]
    pa, ma = results[accum]
    np.testing.assert_allclose(
        float(m1["loss"]), float(ma["loss"]), rtol=1e-6
    )
    assert float(m1["accuracy"]) == float(ma["accuracy"])
    flat1 = jax.tree_util.tree_leaves_with_path(p1)
    flata = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(pa)
    )
    for path, leaf in flat1:
        # f32 reassociation: the scan sums A gradient trees sequentially,
        # the monolithic step reduces over the batch in one pass — equal
        # up to summation order.
        np.testing.assert_allclose(
            np.asarray(leaf),
            np.asarray(flata[jax.tree_util.keystr(path)]),
            rtol=1e-4,
            atol=1e-6,
            err_msg=jax.tree_util.keystr(path),
        )


def test_microbatch_covers_batch_exactly_once(mesh8):
    """The communication-free microbatch split is a permutation: every
    global row appears in exactly one microbatch."""
    from jax.sharding import NamedSharding

    from tpudl.parallel.sharding import active_mesh
    from tpudl.runtime.mesh import batch_partition_spec

    batch = {"x": np.arange(64, dtype=np.int32)}
    sharding = NamedSharding(mesh8, batch_partition_spec())
    placed = {"x": jax.device_put(batch["x"], sharding)}

    with active_mesh(mesh8):
        split = jax.jit(lambda b: microbatch(b, 4))(placed)
    rows = np.asarray(split["x"]).ravel()
    assert sorted(rows.tolist()) == list(range(64))
    # each microbatch has B/A rows
    assert np.asarray(split["x"]).shape == (4, 16)


def test_microbatch_indivisible_raises(mesh8):
    from tpudl.parallel.sharding import active_mesh

    with active_mesh(mesh8):
        with pytest.raises(ValueError, match="not divisible"):
            microbatch({"x": jnp.zeros((12, 2))}, 5)


def test_accumulated_batchnorm_model_trains(mesh8):
    """BatchNorm path: stats thread through the scan; loss descends."""
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.models.resnet import ResNetTiny

    model = ResNetTiny(num_classes=10)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 32, 32, 3)),
        optax.sgd(0.05, momentum=0.9),
    )
    stats0 = jax.tree.map(np.asarray, state.batch_stats)
    step = compile_step(
        make_classification_train_step(accum_steps=4), mesh8, state, None
    )
    rng = jax.random.key(1)
    losses = []
    for b in synthetic_classification_batches(
        64, image_shape=(32, 32, 3), num_classes=10, num_batches=30
    ):
        state, metrics = step(state, b, rng)
        losses.append(float(metrics["loss"]))
    # Plumbing check, not a convergence benchmark: 16-row microbatch BN
    # statistics learn slowly — just require monotone-ish descent.
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.97, losses
    # Running stats moved and stayed finite.
    moved = jax.tree.map(
        lambda a, b: not np.allclose(a, np.asarray(b)), stats0,
        state.batch_stats,
    )
    assert any(jax.tree.leaves(moved))
    assert all(
        np.isfinite(np.asarray(x)).all()
        for x in jax.tree.leaves(state.batch_stats)
    )
