"""Integration smoke (SURVEY.md §4.2): a few steps of the configs[0]-shaped
workload asserting loss decreases — on a tiny model so the CPU backend stays
fast, and on a real 8-fake-device mesh so the pjit path is exercised."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models.resnet import ResNetTiny
from tpudl.parallel.sharding import FSDP_RULES
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train.loop import (
    compile_step,
    create_train_state,
    fit,
    make_classification_eval_step,
    make_classification_train_step,
)


def _make_state(num_classes=4, image=(16, 16, 3), lr=0.05):
    model = ResNetTiny(num_classes=num_classes)
    import jax.numpy as jnp

    sample = jnp.zeros((1, *image))
    tx = optax.sgd(lr, momentum=0.9)
    return create_train_state(jax.random.key(0), model, sample, tx)


def _run(mesh, rules, steps=30, batch=64):
    state = _make_state()
    step = compile_step(
        make_classification_train_step(), mesh, state, rules
    )
    batches = synthetic_classification_batches(
        batch, image_shape=(16, 16, 3), num_classes=4, num_batches=steps
    )
    losses = []
    rng = jax.random.key(1)
    first = None
    for b in batches:
        state, metrics = step(state, b, rng)
        losses.append(float(metrics["loss"]))
    return state, losses


def test_loss_decreases_dp_mesh():
    mesh = make_mesh(MeshSpec(dp=-1))
    state, losses = _run(mesh, rules=None)
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.85, losses


def test_loss_decreases_fsdp_mesh(mesh8):
    state, losses = _run(mesh8, rules=FSDP_RULES, steps=15)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses


def test_eval_step_runs(mesh8):
    state = _make_state()
    eval_step = compile_step(
        make_classification_eval_step(),
        mesh8,
        state,
        rules=None,
        donate_state=False,
        has_rng=False,
    )
    batch = next(
        synthetic_classification_batches(16, image_shape=(16, 16, 3), num_classes=4)
    )
    metrics = eval_step(state, batch)
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_evaluate_weighted_mean():
    """evaluate() aggregates example-weighted means over the dataset."""
    from tpudl.train.loop import evaluate

    state = _make_state()
    mesh = make_mesh(MeshSpec(dp=-1))
    eval_step = compile_step(
        make_classification_eval_step(), mesh, state, None, has_rng=False
    )
    batches = list(
        synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=4, num_batches=3
        )
    )
    out = evaluate(eval_step, state, batches)
    assert set(out) == {"loss", "accuracy"}
    assert np.isfinite(out["loss"]) and 0.0 <= out["accuracy"] <= 1.0
    # Weighted mean equals per-batch mean when batches are equal-sized.
    per_batch = [eval_step(state, b) for b in batches]
    expected = float(np.mean([float(m["loss"]) for m in per_batch]))
    np.testing.assert_allclose(out["loss"], expected, rtol=1e-6)
    with pytest.raises(ValueError, match="no batches"):
        evaluate(eval_step, state, [])
    with pytest.raises(ValueError, match="positive"):
        evaluate(eval_step, state, batches, num_steps=0)


def test_compile_step_warns_per_distinct_rebuilt_tx():
    """Regression (ADVICE round 5): the graft warning fires for EVERY
    distinct rebuilt tx — a second rebuilt state with (possibly
    different) optimizer hyperparameters must not pass silently after
    the first warning spent the once-per-wrapper budget."""
    import warnings

    import optax

    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.runtime.mesh import MeshSpec, make_mesh

    state = _make_state()
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(
        make_classification_train_step(), mesh, state, None,
        donate_state=False,
    )
    batch = next(
        synthetic_classification_batches(
            16, image_shape=(16, 16, 3), num_classes=4
        )
    )
    rng = jax.random.key(1)
    state, _ = step(state, batch, rng)

    def run(s):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            step(s, batch, rng)
        return [x for x in w if "ORIGINALLY-COMPILED" in str(x.message)]

    # First rebuilt tx warns; the SAME rebuilt state again does not
    # (identical object, already flagged); a THIRD state with yet
    # another tx warns again instead of passing silently.
    rebuilt = state.replace(tx=optax.sgd(0.01, momentum=0.9))
    assert len(run(rebuilt)) == 1
    assert len(run(rebuilt)) == 0
    rebuilt2 = state.replace(tx=optax.sgd(0.001, momentum=0.9))
    assert len(run(rebuilt2)) == 1

    # Bounded: a caller rebuilding tx EVERY call gets one suppression
    # notice past the cap, then silence — not a warning (and a retained
    # optimizer object) per step forever.
    tail = [
        run(state.replace(tx=optax.sgd(1e-4 * (k + 1), momentum=0.9)))
        for k in range(10)
    ]
    flat = [str(w.message) for ws in tail for w in ws]
    assert any("not be reported individually" in m for m in flat)
    assert tail[-1] == [] and tail[-2] == []  # past the cap: silent


def test_pad_batch():
    from tpudl.train.loop import pad_batch

    batch = {
        "image": np.ones((3, 4, 4, 1), np.float32),
        "label": np.arange(3, dtype=np.int64),
    }
    padded = pad_batch(batch, 8)
    assert padded["image"].shape == (8, 4, 4, 1)
    assert padded["label"].shape == (8,)
    np.testing.assert_array_equal(
        padded["_valid"], [1, 1, 1, 0, 0, 0, 0, 0]
    )
    np.testing.assert_array_equal(padded["image"][3:], 0.0)
    # Idempotent re-pad extends the mask with zeros.
    repadded = pad_batch(padded, 10)
    np.testing.assert_array_equal(
        repadded["_valid"], [1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
    )
    with pytest.raises(ValueError, match="pad batch"):
        pad_batch(batch, 2)
    with pytest.raises(ValueError, match="ragged"):
        pad_batch({"a": np.ones((3,)), "b": np.ones((4,))}, 8)


def test_evaluate_ragged_tail_pads_not_recompiles(mesh8):
    """A ragged tail smaller than the shard count neither crashes on
    divisibility nor compiles a third executable: evaluate() pads it to
    the leading batch size with a _valid mask, and the weighted metrics
    equal the exact per-batch computation on the real rows."""
    from tpudl.train.loop import evaluate

    state = _make_state()
    raw_step = make_classification_eval_step()
    eval_step = compile_step(
        raw_step, mesh8, state, rules=None, donate_state=False, has_rng=False
    )
    rngs = iter(jax.random.split(jax.random.key(7), 3))

    def mk(n):
        r1, r2 = jax.random.split(next(rngs))
        return {
            "image": np.asarray(jax.random.normal(r1, (n, 16, 16, 3))),
            "label": np.asarray(
                jax.random.randint(r2, (n,), 0, 4), np.int64
            ),
        }

    batches = [mk(16), mk(16), mk(4)]  # tail 4 < 8 devices
    out = evaluate(eval_step, state, batches)
    # Exact reference: unjitted per-batch metrics at true sizes.
    expected_loss = sum(
        float(raw_step(state, b)["loss"]) * b["label"].shape[0]
        for b in batches
    ) / 36.0
    np.testing.assert_allclose(out["loss"], expected_loss, rtol=1e-4)
    assert eval_step.jitted._cache_size() <= 2


def test_evaluate_never_pads_into_mask_unaware_step():
    """A custom eval step without the mask-aware marker keeps the exact
    legacy behavior — the tail runs at its true size (padding zeros into
    a plain-mean step would silently bias its metrics)."""
    from tpudl.train.loop import evaluate

    seen_sizes = []

    def custom_step(state, batch):
        bs = batch["label"].shape[0]
        seen_sizes.append(bs)
        assert "_valid" not in batch
        return {"loss": jnp.mean(batch["label"].astype(jnp.float32))}

    batches = [
        {"label": np.full((n,), 2.0, np.float32)} for n in (8, 8, 2)
    ]
    out = evaluate(custom_step, state=None, batches=batches)
    assert seen_sizes == [8, 8, 2]
    np.testing.assert_allclose(out["loss"], 2.0, rtol=1e-6)
    # Explicit pad_to asserts the caller's step handles _valid.
    seen_sizes.clear()
    padded_seen = []

    def mask_aware_step(state, batch):
        padded_seen.append(batch["label"].shape[0])
        w = batch.get("_valid")
        lab = batch["label"].astype(jnp.float32)
        if w is None:
            return {"loss": jnp.mean(lab)}
        return {"loss": jnp.sum(lab * w) / jnp.maximum(jnp.sum(w), 1.0)}

    out = evaluate(mask_aware_step, state=None, batches=batches, pad_to=8)
    assert padded_seen == [8, 8, 8]
    np.testing.assert_allclose(out["loss"], 2.0, rtol=1e-6)


def test_compile_step_preprocess_runs_inside_jit(mesh8):
    """The device-side preprocessing hook: uint8 wire batches through
    compile_step(preprocess=...) must produce EXACTLY the step outputs of
    host-normalized f32 batches (same arithmetic, traced into the same
    executable), for train (has_rng) and eval (mask-aware marker
    preserved) steps alike."""
    from tpudl.data.datasets import (
        device_normalize_cifar,
        normalize_cifar_batch,
        wire_cifar_batch,
    )

    rng_np = np.random.default_rng(0)
    raw = {
        "image": rng_np.integers(0, 256, (16, 16, 16, 3)).astype(np.uint8),
        "label": rng_np.integers(0, 4, (16,)).astype(np.int64),
    }

    def run(step_factory, batch, **kwargs):
        state = _make_state()
        step = compile_step(
            step_factory, mesh8, state, None, donate_state=False, **kwargs
        )
        _, metrics = step(state, batch, jax.random.key(1))
        return {k: float(v) for k, v in metrics.items()}

    wired = run(
        make_classification_train_step(),
        wire_cifar_batch(raw),
        preprocess=device_normalize_cifar(),
    )
    hosted = run(make_classification_train_step(), normalize_cifar_batch(raw))
    assert wired == pytest.approx(hosted, rel=1e-5)

    # Eval shape: preprocess composes with has_rng=False and keeps the
    # mask-aware marker (evaluate()'s padding decision reads it).
    state = _make_state()
    eval_step = compile_step(
        make_classification_eval_step(), mesh8, state, None,
        has_rng=False, preprocess=device_normalize_cifar(),
    )
    assert eval_step._tpudl_mask_aware
    m = eval_step(state, wire_cifar_batch(raw))
    assert np.isfinite(float(m["loss"]))
