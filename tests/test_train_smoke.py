"""Integration smoke (SURVEY.md §4.2): a few steps of the configs[0]-shaped
workload asserting loss decreases — on a tiny model so the CPU backend stays
fast, and on a real 8-fake-device mesh so the pjit path is exercised."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models.resnet import ResNetTiny
from tpudl.parallel.sharding import FSDP_RULES
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train.loop import (
    compile_step,
    create_train_state,
    fit,
    make_classification_eval_step,
    make_classification_train_step,
)


def _make_state(num_classes=4, image=(16, 16, 3), lr=0.05):
    model = ResNetTiny(num_classes=num_classes)
    import jax.numpy as jnp

    sample = jnp.zeros((1, *image))
    tx = optax.sgd(lr, momentum=0.9)
    return create_train_state(jax.random.key(0), model, sample, tx)


def _run(mesh, rules, steps=30, batch=64):
    state = _make_state()
    step = compile_step(
        make_classification_train_step(), mesh, state, rules
    )
    batches = synthetic_classification_batches(
        batch, image_shape=(16, 16, 3), num_classes=4, num_batches=steps
    )
    losses = []
    rng = jax.random.key(1)
    first = None
    for b in batches:
        state, metrics = step(state, b, rng)
        losses.append(float(metrics["loss"]))
    return state, losses


def test_loss_decreases_dp_mesh():
    mesh = make_mesh(MeshSpec(dp=-1))
    state, losses = _run(mesh, rules=None)
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.85, losses


def test_loss_decreases_fsdp_mesh(mesh8):
    state, losses = _run(mesh8, rules=FSDP_RULES, steps=15)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses


def test_eval_step_runs(mesh8):
    state = _make_state()
    eval_step = compile_step(
        make_classification_eval_step(),
        mesh8,
        state,
        rules=None,
        donate_state=False,
        has_rng=False,
    )
    batch = next(
        synthetic_classification_batches(16, image_shape=(16, 16, 3), num_classes=4)
    )
    metrics = eval_step(state, batch)
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_evaluate_weighted_mean():
    """evaluate() aggregates example-weighted means over the dataset."""
    from tpudl.train.loop import evaluate

    state = _make_state()
    mesh = make_mesh(MeshSpec(dp=-1))
    eval_step = compile_step(
        make_classification_eval_step(), mesh, state, None, has_rng=False
    )
    batches = list(
        synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=4, num_batches=3
        )
    )
    out = evaluate(eval_step, state, batches)
    assert set(out) == {"loss", "accuracy"}
    assert np.isfinite(out["loss"]) and 0.0 <= out["accuracy"] <= 1.0
    # Weighted mean equals per-batch mean when batches are equal-sized.
    per_batch = [eval_step(state, b) for b in batches]
    expected = float(np.mean([float(m["loss"]) for m in per_batch]))
    np.testing.assert_allclose(out["loss"], expected, rtol=1e-6)
    with pytest.raises(ValueError, match="no batches"):
        evaluate(eval_step, state, [])
    with pytest.raises(ValueError, match="positive"):
        evaluate(eval_step, state, batches, num_steps=0)
