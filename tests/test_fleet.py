"""Fleet observability plane (ISSUE 10 tentpole, tpudl.obs.fleet).

The contract under test: a FleetMonitor scraping N live exporters over
REAL HTTP merges their registries into ONE labeled Prometheus
exposition (``serve_slots_busy{source="a"}`` — one TYPE line per
metric, one series per source, no mangled names) and a health rollup
in which one sick member is a sick fleet; each member's ``/snapshot``
names its active span stream so trace discovery needs no out-of-band
config; and ``report.py --request`` / ``--fleet`` stitch records
merged from SEVERAL processes' streams into one router-door -> queue
-> prefill -> decode timeline whose hop decomposition (all durations,
never cross-clock timestamp subtraction) sums to the router-measured
TTFT — with a loud "partial trace" warning when a hop named by a
router event has no stream on disk."""

import json
import re
import urllib.error
import urllib.request

import pytest

import tpudl.obs as obs
from tpudl.obs import counters as obs_counters
from tpudl.obs import exporter as obs_exporter
from tpudl.obs import report as obs_report
from tpudl.obs.fleet import FleetMonitor, render_fleet_prometheus
from tpudl.obs.spans import SpanRecorder


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("TPUDL_OBS_PORT", raising=False)
    monkeypatch.delenv("TPUDL_OBS_DIR", raising=False)
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter.stop_exporter()
    obs_exporter._reset_health_for_tests()
    yield
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter.stop_exporter()
    obs_exporter._reset_health_for_tests()


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# The PR-6 conformance regex, verbatim: labeled series must still be
# legal exposition lines.
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"[^\"]*\")*\})?"
    r" (NaN|[+-]?Inf|[+-]?[0-9]*\.?[0-9]+([eE][+-]?[0-9]+)?)$"
)


# ---------------------------------------------------------------------------
# render_prometheus label support (satellite: unlabeled stays
# byte-identical)
# ---------------------------------------------------------------------------


def _sample_snapshot():
    reg = obs_counters.Registry()
    reg.counter("bytes_ingested").inc(1234)
    reg.gauge("serve_slots_busy").set(3)
    h = reg.histogram("serve_ttft_ms")
    for v in [10.0, 20.0, 30.0, 40.0]:
        h.observe(v)
    return reg.snapshot()


def test_render_prometheus_unlabeled_output_byte_identical():
    """The pre-label renderer's exact bytes, locked down: the label
    feature must not move a single character of the unlabeled path."""
    snap = _sample_snapshot()
    text = obs_exporter.render_prometheus(snap, {"train_loop": 2.5})
    assert text == (
        "# TYPE bytes_ingested counter\n"
        "bytes_ingested 1234.0\n"
        "# TYPE serve_slots_busy gauge\n"
        "serve_slots_busy 3.0\n"
        "# TYPE serve_ttft_ms summary\n"
        'serve_ttft_ms{quantile="0.5"} 25.0\n'
        'serve_ttft_ms{quantile="0.95"} 38.5\n'
        'serve_ttft_ms{quantile="0.99"} 39.699999999999996\n'
        "serve_ttft_ms_sum 100.0\n"
        "serve_ttft_ms_count 4\n"
        "# TYPE train_loop_heartbeat_age_s gauge\n"
        "train_loop_heartbeat_age_s 2.5\n"
    )
    # labels=None and labels={} are the same (byte-identical) path.
    assert obs_exporter.render_prometheus(snap, labels={}) == (
        obs_exporter.render_prometheus(snap)
    )


def test_render_prometheus_labels_attach_to_every_series():
    snap = _sample_snapshot()
    text = obs_exporter.render_prometheus(
        snap, {"train_loop": 2.5}, labels={"source": "replica1"}
    )
    lines = text.strip().splitlines()
    for line in lines:
        if line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), line
        assert 'source="replica1"' in line, line
    assert 'serve_slots_busy{source="replica1"} 3.0' in lines
    # Quantile rows merge the label set with their quantile label.
    assert (
        'serve_ttft_ms{quantile="0.5",source="replica1"} 25.0' in lines
    )
    assert 'serve_ttft_ms_count{source="replica1"} 4' in lines
    # Label values are escaped, label names validated.
    esc = obs_exporter.render_prometheus(
        {"gauges": {"g": 1.0}}, labels={"source": 'a"b\\c'}
    )
    assert 'g{source="a\\"b\\\\c"} 1.0' in esc
    with pytest.raises(ValueError, match="label name"):
        obs_exporter.render_prometheus(
            {"gauges": {"g": 1.0}}, labels={"bad-name": "x"}
        )


def test_render_fleet_prometheus_groups_type_lines_once():
    snap = _sample_snapshot()
    text = render_fleet_prometheus({"b": snap, "a": snap})
    lines = text.strip().splitlines()
    # One TYPE line per metric, both sources' series under it.
    assert lines.count("# TYPE serve_slots_busy gauge") == 1
    i = lines.index("# TYPE serve_slots_busy gauge")
    assert lines[i + 1] == 'serve_slots_busy{source="a"} 3.0'
    assert lines[i + 2] == 'serve_slots_busy{source="b"} 3.0'
    for line in lines:
        if not line.startswith("#"):
            assert _PROM_LINE.match(line), line


# ---------------------------------------------------------------------------
# The two-exporter real-HTTP scrape -> merged labeled /metrics
# (the satellite's acceptance test)
# ---------------------------------------------------------------------------


def test_fleet_monitor_merges_two_real_exporters_over_http():
    reg_a, reg_b = obs_counters.Registry(), obs_counters.Registry()
    reg_a.gauge("serve_slots_busy").set(1)
    reg_b.gauge("serve_slots_busy").set(4)
    reg_a.counter("serve_requests_completed").inc(10)
    reg_b.counter("serve_requests_completed").inc(20)
    ex_a = obs_exporter.ObsExporter(port=0, registry=reg_a).start()
    ex_b = obs_exporter.ObsExporter(port=0, registry=reg_b).start()
    fleet = FleetMonitor({
        "a": f"http://127.0.0.1:{ex_a.port}/snapshot",
        "b": f"http://127.0.0.1:{ex_b.port}/snapshot",
    }, scrape_interval_s=0.0)
    try:
        fleet.start(port=0)
        status, text = _get(f"http://127.0.0.1:{fleet.port}/metrics")
        assert status == 200
        lines = text.strip().splitlines()
        for line in lines:
            if not line.startswith("#"):
                assert _PROM_LINE.match(line), line
        assert 'serve_slots_busy{source="a"} 1.0' in lines
        assert 'serve_slots_busy{source="b"} 4.0' in lines
        assert 'serve_requests_completed{source="a"} 10.0' in lines
        assert 'serve_requests_completed{source="b"} 20.0' in lines
        # The fleet's own plane: rollup + per-source scrape gauges.
        assert "fleet_sources_total 2.0" in lines
        assert "fleet_sources_healthy 2.0" in lines
        assert 'fleet_source_up{source="a"} 1.0' in lines
        assert any(
            l.startswith('fleet_scrape_age_s{source="a"}') for l in lines
        )
        status, body = _get(f"http://127.0.0.1:{fleet.port}/fleet")
        rollup = json.loads(body)
        assert rollup["healthy"] is True
        assert rollup["sources"]["a"]["ok"] is True
        status, _ = _get(f"http://127.0.0.1:{fleet.port}/healthz")
        assert status == 200

        # One member dies: its last-good metrics stay visible (age
        # says how stale), but the rollup flips and /healthz probes
        # 503 — one sick member is a sick fleet.
        ex_b.close()
        fleet.scrape(force=True)
        _, text = _get(f"http://127.0.0.1:{fleet.port}/metrics")
        lines = text.strip().splitlines()
        assert 'serve_slots_busy{source="b"} 4.0' in lines  # last good
        assert 'fleet_source_up{source="b"} 0.0' in lines
        assert any(
            l.startswith('fleet_scrape_failures_total{source="b"} ')
            and not l.endswith(" 0.0")
            for l in lines
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.port}/healthz", timeout=10.0
            )
        assert ei.value.code == 503
        rollup = json.load(ei.value)
        assert rollup["healthy"] is False
        assert rollup["sources"]["b"]["healthy"] is False
        assert rollup["sources"]["b"]["error"]
    finally:
        fleet.close()
        ex_a.close()
        ex_b.close()


def test_fleet_monitor_in_process_sources_and_membership():
    reg = obs_counters.Registry()
    reg.gauge("g").set(7)
    ex = obs_exporter.ObsExporter(port=0, registry=reg)
    fleet = FleetMonitor({"self": ex.snapshot}, scrape_interval_s=0.0)
    snap = fleet.fleet_snapshot()
    assert snap["healthy"] is True and snap["sources_total"] == 1
    assert 'g{source="self"} 7.0' in fleet.metrics_text()
    fleet.add_source("other", lambda: {"registry": {"gauges": {"g": 9}}})
    assert 'g{source="other"} 9.0' in fleet.metrics_text()
    fleet.remove_source("other")
    assert "other" not in fleet.fleet_snapshot()["sources"]
    with pytest.raises(ValueError, match="at least one source"):
        FleetMonitor({})


def test_fleet_rollup_reports_burning_member():
    """A member whose health names a burning SLO objective surfaces in
    burning_sources — the autoscaler's cross-process pressure signal."""
    def snapshot():
        return {
            "registry": {},
            "health": {
                "healthy": False,
                "sources": {
                    "slo": {"healthy": False, "burning": ["ttft_p99"]},
                },
            },
        }

    fleet = FleetMonitor({"replica1": snapshot}, scrape_interval_s=0.0)
    snap = fleet.fleet_snapshot()
    assert snap["burning_sources"] == ["replica1"]
    assert snap["sources"]["replica1"]["burning"] == ["ttft_p99"]
    assert snap["healthy"] is False
    assert fleet.burning_sources() == ["replica1"]


# ---------------------------------------------------------------------------
# /snapshot span-path discovery (satellite) -> fleet trace stitching
# ---------------------------------------------------------------------------


def test_snapshot_names_span_stream_and_fleet_discovers_it(tmp_path):
    import os

    rec = obs.enable(str(tmp_path / "obs"))
    rec.event("request_routed", "serve_request", request_id="r1",
              replica="r0")
    ex = obs_exporter.ObsExporter(port=0)
    snap = ex.snapshot()
    assert snap["span_path"] == os.path.abspath(rec.path)
    fleet = FleetMonitor({"router": ex.snapshot}, scrape_interval_s=0.0)
    assert fleet.trace_paths() == {"router": os.path.abspath(rec.path)}
    records = fleet.trace_records()
    assert any(
        r.get("name") == "request_routed" and r.get("request_id") == "r1"
        for r in records
    )
    # Without recording active there is no stream to discover.
    obs.disable()
    assert ex.snapshot()["span_path"] is None


# ---------------------------------------------------------------------------
# Cross-process --request stitching (satellite: merge all streams,
# decomposition sums to the router TTFT, partial-trace warning)
# ---------------------------------------------------------------------------


def _write_fleet_streams(tmp_path, with_replica_stream=True):
    """Synthesize a two-process fleet trace: the ROUTER process's
    stream (door + failover-free) and the REPLICA process's stream
    (inbox dequeue, admission, prefill, decode, served, complete) with
    DISJOINT clock epochs — the stitcher must never subtract across
    them. Durations are the ground truth:
      inbox 0.010 + queue 0.020 + prefill 0.050 = router TTFT 0.080
    """
    obs_dir = tmp_path / "fleet-obs"
    obs_dir.mkdir()
    router = SpanRecorder(
        str(obs_dir / "spans-router-p0-100.jsonl"),
        host="router-host", process=0,
    )
    router.event(
        "request_routed", "serve_request", request_id="rq",
        replica="rep1", priority=0,
    )
    router.close()
    if not with_replica_stream:
        return str(obs_dir)
    t = [1000.0]  # a clock epoch unrelated to the router's
    rep = SpanRecorder(
        str(obs_dir / "spans-rep1-p0-200.jsonl"),
        clock=lambda: t[0], host="rep1-host", process=0,
    )
    rep.event(
        "replica_dequeue", "serve_request", request_id="rq",
        replica="rep1", inbox_wait_s=0.010,
    )
    rep.event(
        "request_queued", "serve_request", request_id="rq",
        req_priority=0, depth=1,
    )
    t[0] = 1000.020
    rep.record("prefill", "serve_prefill", 1000.020, 0.050,
               {"request_id": "rq", "slot": 0,
                "queue_wait_s": 0.020})
    t[0] = 1000.070
    rep.record("decode_step", "serve_decode", 1000.072, 0.004,
               {"busy": 1, "rids": ["rq"]})
    rep.record("decode_step", "serve_decode", 1000.078, 0.004,
               {"busy": 1, "rids": ["rq"]})
    t[0] = 1000.082
    rep.event(
        "request_complete", "serve_request", request_id="rq",
        finish_reason="length", ttft_s=0.070, tpot_s=0.006,
        queue_wait_s=0.020, generation_s=0.012, num_tokens=3,
    )
    rep.event(
        "request_served", "serve_request", request_id="rq",
        replica="rep1", finish_reason="length",
        inbox_wait_s=0.010, router_ttft_s=0.080,
    )
    rep.close()
    return str(obs_dir)


def test_cross_process_request_stitch_decomposition_sums(tmp_path):
    obs_dir = _write_fleet_streams(tmp_path)
    records = obs_report.load_records([obs_dir])  # merges BOTH streams
    tl = obs_report.build_request_timeline(records, "rq")
    assert tl["warnings"] == []
    assert tl["hops"]["routed"] is True
    assert tl["hops"]["replica"] == "rep1"
    assert tl["hops"]["multi_process"] is True
    assert len(tl["hops"]["processes"]) == 2
    # Logical hop order, never cross-clock timestamp order (the router
    # epoch is near 0, the replica's near 1000 — ts-sorting would put
    # the door LAST).
    whats = [e["what"] for e in tl["timeline"]]
    assert whats == [
        "routed", "replica_dequeue", "queued", "prefill",
        "decode_chunk", "decode_chunk", "served", "complete",
    ]
    d = tl["decomposition"]
    # The acceptance identity: hop durations sum to the
    # router-measured TTFT.
    assert d["inbox_wait_s"] == pytest.approx(0.010)
    assert d["router_ttft_s"] == pytest.approx(0.080)
    assert (
        d["inbox_wait_s"] + d["queue_wait_s"] + d["prefill_s"]
        == pytest.approx(d["router_ttft_s"], rel=1e-6)
    )
    assert d["router_accounted_s"] == pytest.approx(0.080, rel=1e-6)


def test_partial_trace_warning_when_hop_stream_missing(tmp_path, capsys):
    """The satellite's failure mode: the router stream names replica
    'rep1' but that process's span file never made it into the merge —
    the stitch must WARN loudly, not render a silently-empty trace."""
    obs_dir = _write_fleet_streams(tmp_path, with_replica_stream=False)
    records = obs_report.load_records([obs_dir])
    tl = obs_report.build_request_timeline(records, "rq")
    assert any("partial trace" in w for w in tl["warnings"])
    assert any("rep1" in w for w in tl["warnings"])
    assert any("no completion event" in w for w in tl["warnings"])
    # And the CLI prints it.
    assert obs_report.main([obs_dir, "--request", "rq"]) == 0
    out = capsys.readouterr().out
    assert "WARNING" in out and "partial trace" in out


def test_report_fleet_cli(tmp_path, capsys):
    obs_dir = _write_fleet_streams(tmp_path)
    assert obs_report.main([obs_dir, "--fleet"]) == 0
    out = capsys.readouterr().out
    assert "tpudl fleet report" in out
    assert "2 process stream(s)" in out
    assert "router TTFT" in out
    assert "replica inbox wait" in out
    assert "PARTIAL TRACES" not in out
    # --json round-trips the structure.
    assert obs_report.main([obs_dir, "--fleet", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["num_requests"] == 1
    assert rep["router_ttft"]["count"] == 1
    assert rep["router_ttft"]["mean_ms"] == pytest.approx(80.0)
    assert rep["partial_traces"] == {}


def test_report_fleet_flags_partial_traces(tmp_path, capsys):
    obs_dir = _write_fleet_streams(tmp_path, with_replica_stream=False)
    assert obs_report.main([obs_dir, "--fleet"]) == 0
    out = capsys.readouterr().out
    assert "PARTIAL TRACES" in out and "rep1" in out


def test_fleet_chrome_trace_one_track_per_process(tmp_path):
    """The merged fleet records export as a Chrome trace with one pid
    (track) per recording process — the Perfetto view of one request's
    cross-process life."""
    from tpudl.obs.spans import chrome_trace_events

    obs_dir = _write_fleet_streams(tmp_path)
    records = obs_report.load_records([obs_dir])
    events = chrome_trace_events(records)
    names = {
        e["args"]["name"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert len(names) == 2
    assert any("router-host" in n for n in names)
    assert any("rep1-host" in n for n in names)


def test_dead_member_stale_burn_is_not_pressure():
    """Review regression: a member whose LAST GOOD snapshot showed a
    burning SLO then became unreachable must read as unhealthy —
    NOT as still-burning, or a crashed replica would feed the
    autoscaler permanent pressure and pin the fleet at max_replicas."""
    state = {"alive": True}

    def snapshot():
        if not state["alive"]:
            raise ConnectionError("member gone")
        return {
            "registry": {},
            "health": {
                "healthy": False,
                "sources": {
                    "slo": {"healthy": False, "burning": ["ttft_p99"]},
                },
            },
        }

    fleet = FleetMonitor({"m": snapshot}, scrape_interval_s=0.0)
    assert fleet.burning_sources() == ["m"]  # alive and burning
    state["alive"] = False
    fleet.scrape(force=True)
    snap = fleet.fleet_snapshot()
    assert snap["burning_sources"] == []  # stale burn is not a burn
    assert snap["sources"]["m"]["ok"] is False
    assert snap["sources"]["m"]["healthy"] is False
    assert snap["healthy"] is False  # still a sick fleet, just not burning


def test_replica_inbox_shed_trace_is_not_partial(tmp_path):
    """Review regression: a request shed AT THE REPLICA INBOX leaves
    routed + replica_dequeue + (replica-recorded) completion — its
    dequeue record proves the hop's stream IS in the merge, so the
    stitch must not claim spans are missing from disk."""
    rec = SpanRecorder(
        str(tmp_path / "spans-h-p0-1.jsonl"), host="h", process=0
    )
    rec.event("request_routed", "serve_request", request_id="late",
              replica="r0", priority=0)
    rec.event("replica_dequeue", "serve_request", request_id="late",
              replica="r0", inbox_wait_s=2.0)
    rec.event("request_complete", "serve_request", request_id="late",
              finish_reason="shed_timeout", queue_wait_s=2.0,
              num_tokens=0, shed_by="replica_inbox")
    rec.close()
    records = obs_report.load_records([str(tmp_path)])
    tl = obs_report.build_request_timeline(records, "late")
    assert tl["warnings"] == []
    assert tl["finish_reason"] == "shed_timeout"
    # And even WITHOUT the completion record (shed mid-flight), the
    # dequeue alone proves the hop stream is present: only the
    # "no completion" warning may fire, never "no spans on disk".
    tl2 = obs_report.build_request_timeline(records[:2], "late")
    assert len(tl2["warnings"]) == 1
    assert "no completion event" in tl2["warnings"][0]


def test_fleet_monitor_lock_order_wrapped_and_clean(monkeypatch):
    """TPUDL_DEBUG_LOCK_ORDER wraps the FleetMonitor's lock in the
    ordered-lock monitor; scrape + rollup under it record no
    violations (the fleet half of the router/fleet runtime lock-order
    coverage)."""
    from tpudl.analysis import concurrency as conc

    monitor = conc.LockOrderMonitor()
    monkeypatch.setattr(conc, "_default_monitor", monitor)
    monkeypatch.setenv("TPUDL_DEBUG_LOCK_ORDER", "1")
    with obs_exporter.ObsExporter(port=0) as ex:
        obs_counters.registry().counter("serve_decode_steps").inc(3)
        fleet = FleetMonitor({"self": ex.snapshot}, scrape_interval_s=0.0)
        assert isinstance(fleet._lock, conc.OrderedLock)
        fleet.scrape()
        roll = fleet.fleet_snapshot()
    assert roll["sources"]["self"]["ok"]
    assert monitor.violations == []


def test_scrape_retry_absorbs_transient_hiccup():
    """The retry satellite: a member that fails ONE attempt and
    answers the in-band retry records a clean poll — no
    fleet_scrape_failures_total bump, no aged member (before, a single
    transient HTTP hiccup immediately failed the poll)."""
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("transient hiccup")
        return {"registry": {"gauges": {"g": 1}}}

    slept = []
    fleet = FleetMonitor(
        {"m": flaky}, scrape_interval_s=60.0, sleep=slept.append
    )
    fleet.scrape(force=True)
    snap = fleet.fleet_snapshot()
    assert snap["sources"]["m"]["ok"] is True
    assert snap["sources"]["m"]["scrape_failures"] == 0
    assert calls["n"] == 2  # first attempt + the one in-band retry
    assert len(slept) == 1 and slept[0] > 0


def test_scrape_retry_backoff_grows_with_jitter():
    """A persistently-down member costs exactly one retry per poll
    (failures count polls, not attempts), and the backoff before the
    retry grows exponentially with the failure streak while staying
    inside the jitter band (0.5x..1.5x of the capped base)."""

    def dead():
        raise ConnectionError("down")

    slept = []
    fleet = FleetMonitor(
        {"m": dead}, scrape_interval_s=60.0,
        retry_backoff_s=0.1, retry_backoff_max_s=10.0,
        sleep=slept.append,
    )
    for poll in range(3):
        fleet.scrape(force=True)
    snap = fleet.fleet_snapshot()
    assert snap["sources"]["m"]["scrape_failures"] == 3
    assert len(slept) == 3
    for i, delay in enumerate(slept):
        base = 0.1 * (2 ** i)  # failure streak at retry time = i
        assert 0.5 * base <= delay <= 1.5 * base, (i, delay)


@pytest.mark.chaos
def test_scrape_blackhole_chaos_consumes_retry_budget():
    """tpudl.serve.chaos scrape blackhole: fail_n counts ATTEMPTS, so
    fail_n=1 is absorbed by the retry (clean poll) while fail_n=3
    fails the first poll outright and recovers on the next."""
    from tpudl.serve import chaos

    def snapshot():
        return {"registry": {"gauges": {"g": 1}}}

    slept = []
    fleet = FleetMonitor(
        {"m": snapshot}, scrape_interval_s=60.0, sleep=slept.append
    )
    fleet.scrape_fault = chaos.make_scrape_fault(fail_n=1)
    fleet.scrape(force=True)
    assert fleet.fleet_snapshot()["sources"]["m"]["scrape_failures"] == 0
    fleet.scrape_fault = chaos.make_scrape_fault(fail_n=3)
    fleet.scrape(force=True)  # attempts 1+2 blackholed -> failed poll
    assert fleet.fleet_snapshot()["sources"]["m"]["scrape_failures"] == 1
    fleet.scrape(force=True)  # attempt 3 blackholed, retry answers
    snap = fleet.fleet_snapshot()
    assert snap["sources"]["m"]["scrape_failures"] == 1
    assert snap["sources"]["m"]["ok"] is True
