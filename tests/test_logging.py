"""Structured metrics logging (SURVEY.md §5.5 — the reference only ever
print()s; reference notebooks/cv/onnx_experiments.py:100,104,140)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytest

from tpudl.train import MetricLogger


@pytest.fixture(scope="module")
def tiny_cv_step():
    """(state, compiled step) for a tiny ResNet — shared across the fit()
    integration tests (compiling ResNet18 on CPU is the slow part)."""
    from tpudl.models import ResNet18
    from tpudl.runtime import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = ResNet18(num_classes=10, small_inputs=True)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.1),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(
        make_classification_train_step(), mesh, state, None, donate_state=False
    )
    return state, step


def test_jsonl_sink(tmp_path):
    d = str(tmp_path / "run")
    with MetricLogger(d, tensorboard=False) as ml:
        ml.log(1, {"loss": 0.5, "accuracy": 0.9})
        ml.log(2, {"loss": jnp.asarray(0.25), "accuracy": 0.95})
    lines = [
        json.loads(line)
        for line in open(os.path.join(d, "metrics.jsonl"))
    ]
    assert lines[0] == {"step": 1, "loss": 0.5, "accuracy": 0.9}
    assert lines[1]["loss"] == 0.25


def test_tensorboard_sink(tmp_path):
    d = str(tmp_path / "tb")
    with MetricLogger(d, tensorboard=True) as ml:
        ml.log(1, {"loss": 1.0})
    # a tfevents file appears when the writer is available; JSONL always.
    files = os.listdir(d)
    assert "metrics.jsonl" in files
    assert any("tfevents" in f for f in files)


def test_stdlog_only_no_dir(caplog):
    import logging

    ml = MetricLogger(log_dir=None)
    with caplog.at_level(logging.INFO, logger="tpudl.metrics"):
        ml.log(3, {"loss": 0.125})
    assert "step=3" in caplog.text and "loss=0.125" in caplog.text


def test_obs_fan_in_tolerates_reserved_metric_names(tmp_path):
    """Metrics ride the obs stream NESTED: a metric literally named
    'step' or 'ts' must neither crash the log call nor corrupt the
    event record's reserved fields."""
    import tpudl.obs as obs
    from tpudl.obs import counters as obs_counters

    rec = obs.enable(str(tmp_path))
    try:
        ml = MetricLogger(log_dir=None, stdlog=False)
        ml.log(7, {"step": 5.0, "ts": 2.0, "loss": 0.1})
        ev = [r for r in rec.records if r.get("kind") == "event"][0]
        assert ev["step"] == 7  # the fit-step index, not the metric
        assert ev["metrics"] == {"step": 5.0, "ts": 2.0, "loss": 0.1}
    finally:
        obs.disable()
        obs_counters.registry().reset()


def test_as_fit_logger_callback(tmp_path, tiny_cv_step):
    """MetricLogger plugs straight into fit(logger=...)."""
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.train import fit

    state, step = tiny_cv_step
    d = str(tmp_path / "fitlog")
    with MetricLogger(d, tensorboard=False) as ml:
        fit(
            step,
            state,
            synthetic_classification_batches(
                8, image_shape=(16, 16, 3), num_batches=4
            ),
            jax.random.key(1),
            log_every=2,
            logger=ml,
        )
    lines = [json.loads(x) for x in open(os.path.join(d, "metrics.jsonl"))]
    assert [x["step"] for x in lines] == [2, 4]
    assert all(np.isfinite(x["loss"]) for x in lines)


def test_fit_profiler_hook_writes_trace(tmp_path, tiny_cv_step):
    """fit(profile_dir=...) captures the configured step window with
    jax.profiler and leaves a TensorBoard-readable trace on disk
    (SURVEY.md §5.1)."""
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.train import fit

    state, step = tiny_cv_step
    prof_dir = str(tmp_path / "trace")
    fit(
        step,
        state,
        synthetic_classification_batches(8, image_shape=(16, 16, 3), num_batches=6),
        jax.random.key(1),
        profile_dir=prof_dir,
        profile_window=(1, 3),
    )
    trace_files = [
        os.path.join(root, f)
        for root, _, files in os.walk(prof_dir)
        for f in files
    ]
    assert trace_files, "profiler wrote no trace files"
