"""The driver contracts must keep working (see __graft_entry__.py)."""

import jax

import __graft_entry__ as graft


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_entry_signature():
    fn, args = graft.entry()
    # Shape-check the flagship forward without paying for a CPU compile.
    out = jax.eval_shape(fn, *args)
    assert out.shape == (8, 1000)
