"""The driver contracts must keep working (see __graft_entry__.py)."""

import jax
import pytest

import __graft_entry__ as graft


@pytest.mark.needs_multiprocess
def test_dryrun_multichip_8():
    # Spawns a real multi-process cohort whose pjit programs this
    # container's CPU jaxlib cannot compile ("Multiprocess computations
    # aren't implemented on the CPU backend") — conftest auto-skips it
    # here with a loud reason; the driver's TPU environment runs it.
    graft.dryrun_multichip(8)


def test_bert_dryrun_params_actually_tp_sharded():
    """Round-1 regression (VERDICT.md weak #3): the dryrun's tp axis was
    decorative. The BERT path must raise if nothing shards over tp, and
    here we additionally check the attention projections specifically."""
    import jax.numpy as jnp
    import optax

    from tpudl.models.bert import BertConfig, BertForSequenceClassification
    from tpudl.parallel.sharding import TP_TRANSFORMER_RULES
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=2, tp=2))
    cfg = BertConfig(
        vocab_size=256, hidden_size=64, num_layers=1, num_heads=4,
        intermediate_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0, dtype=jnp.float32,
    )
    state = create_train_state(
        jax.random.key(0),
        BertForSequenceClassification(cfg),
        jnp.zeros((1, 32), jnp.int32),
        optax.adamw(1e-3),
        init_kwargs={"train": False},
    )
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh,
        state,
        TP_TRANSFORMER_RULES,
    )
    from tpudl.parallel.sharding import _path_str

    by_path = {
        _path_str(p): str(sh.spec)
        for p, sh in jax.tree_util.tree_leaves_with_path(
            step.state_shardings.params
        )
    }
    qkv = [s for path, s in by_path.items()
           if "query/kernel" in path or "intermediate/kernel" in path]
    assert qkv and all("tp" in s for s in qkv), by_path


def test_entry_signature():
    fn, args = graft.entry()
    # Shape-check the flagship forward without paying for a CPU compile.
    out = jax.eval_shape(fn, *args)
    assert out.shape == (8, 1000)
