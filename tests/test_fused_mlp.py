"""Fused bias+GeLU / SwiGLU kernel parity vs the XLA composites.

Interpreter-mode Pallas on the CPU backend (hermetic tier). The GeLU is
the EXACT (erf) variant — the parity target is
``jax.nn.gelu(x + b, approximate=False)``, matching what
tpudl.models.bert always computed — and the backward is recompute-free
(closed-form in the saved inputs), so gradient parity is the real
contract under test."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.mlp_fused import (
    bias_gelu,
    bias_gelu_ref,
    swiglu,
    swiglu_ref,
)


def _arrs(rng, n=29, f=100, dtype=jnp.float32):
    x = jnp.asarray(rng.normal(size=(n, f)) * 2.0, dtype)
    u = jnp.asarray(rng.normal(size=(n, f)) * 2.0, dtype)
    b = jnp.asarray(rng.normal(size=(f,)) * 0.5, jnp.float32)
    return x, u, b


@pytest.mark.parametrize("n,f", [(29, 100), (16, 128), (70, 300)])
def test_bias_gelu_forward_parity(rng_np, n, f):
    x, _, b = _arrs(rng_np, n, f)
    np.testing.assert_allclose(
        np.asarray(bias_gelu(x, b, impl="fused")),
        np.asarray(bias_gelu_ref(x, b)),
        rtol=1e-5, atol=1e-5,
    )


def test_bias_gelu_gradient_parity(rng_np):
    x, _, b = _arrs(rng_np)
    gf = jax.grad(
        lambda x, b: jnp.sum(bias_gelu(x, b, impl="fused") ** 2),
        argnums=(0, 1),
    )(x, b)
    gr = jax.grad(
        lambda x, b: jnp.sum(bias_gelu_ref(x, b) ** 2), argnums=(0, 1)
    )(x, b)
    for name, a, r in zip(("dx", "dbias"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-4,
            err_msg=f"{name} mismatch",
        )


def test_bias_gelu_exact_not_tanh(rng_np):
    """The kernel must implement the erf GeLU: at moderate |x| the tanh
    approximation differs by ~1e-3, well above the fused-vs-ref bar."""
    x = jnp.linspace(-4.0, 4.0, 128).reshape(8, 16)
    b = jnp.zeros((16,))
    fused = np.asarray(bias_gelu(x, b, impl="fused"))
    exact = np.asarray(jax.nn.gelu(x, approximate=False))
    tanh = np.asarray(jax.nn.gelu(x, approximate=True))
    assert np.abs(fused - exact).max() < 1e-5
    assert np.abs(fused - tanh).max() > 1e-4  # would fail for tanh-gelu


@pytest.mark.parametrize("n,f", [(29, 100), (16, 128), (70, 300)])
def test_swiglu_forward_parity(rng_np, n, f):
    g, u, _ = _arrs(rng_np, n, f)
    np.testing.assert_allclose(
        np.asarray(swiglu(g, u, impl="fused")),
        np.asarray(swiglu_ref(g, u)),
        rtol=1e-5, atol=1e-5,
    )


def test_swiglu_gradient_parity(rng_np):
    g, u, _ = _arrs(rng_np)
    gf = jax.grad(
        lambda g, u: jnp.sum(swiglu(g, u, impl="fused") ** 2),
        argnums=(0, 1),
    )(g, u)
    gr = jax.grad(
        lambda g, u: jnp.sum(swiglu_ref(g, u) ** 2), argnums=(0, 1)
    )(g, u)
    for name, a, r in zip(("dgate", "dup"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-4,
            err_msg=f"{name} mismatch",
        )


def test_bf16_tolerance_and_dtype(rng_np):
    x, u, b = _arrs(rng_np, dtype=jnp.bfloat16)
    y = bias_gelu(x, b, impl="fused")
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(bias_gelu_ref(x, b), np.float32),
        rtol=0.05, atol=0.02,
    )
    z = swiglu(x, u, impl="fused")
    assert z.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(z, np.float32),
        np.asarray(swiglu_ref(x, u), np.float32),
        rtol=0.05, atol=0.02,
    )


def test_3d_inputs_and_auto_cpu_fallback(rng_np):
    g = jnp.asarray(rng_np.normal(size=(2, 7, 100)), jnp.float32)
    u = jnp.asarray(rng_np.normal(size=(2, 7, 100)), jnp.float32)
    fused = swiglu(g, u, impl="fused")
    assert fused.shape == g.shape
    auto = swiglu(g, u, impl="auto")
    assert (np.asarray(auto) == np.asarray(swiglu_ref(g, u))).all()
