"""Optimizer construction (tpudl.train.optim) from OptimConfig."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpudl.config import OptimConfig, get_config
from tpudl.train.optim import make_optimizer, make_schedule


def _adam_mu_leaves(opt_state):
    """First-moment leaves of an optax adamw state chain."""
    mus = []
    for s in jax.tree.leaves(opt_state, is_leaf=lambda x: hasattr(x, "mu")):
        if hasattr(s, "mu"):
            mus.extend(jax.tree.leaves(s.mu))
    return mus


def test_mu_dtype_bf16_halves_first_moment():
    cfg = OptimConfig(name="adamw", mu_dtype="bfloat16")
    tx = make_optimizer(cfg)
    params = {"w": jnp.zeros((4, 4), jnp.float32)}
    state = tx.init(params)
    mus = _adam_mu_leaves(state)
    assert mus and all(m.dtype == jnp.bfloat16 for m in mus)
    # nu (second moment) stays f32 for range.
    for s in jax.tree.leaves(state, is_leaf=lambda x: hasattr(x, "nu")):
        if hasattr(s, "nu"):
            assert all(
                n.dtype == jnp.float32 for n in jax.tree.leaves(s.nu)
            )


def test_mu_dtype_default_is_f32():
    tx = make_optimizer(OptimConfig(name="adamw"))
    state = tx.init({"w": jnp.zeros((2,), jnp.float32)})
    mus = _adam_mu_leaves(state)
    assert mus and all(m.dtype == jnp.float32 for m in mus)


def test_bert_configs_opt_into_bf16_mu():
    assert get_config("sst2_bert_base").optim.mu_dtype == "bfloat16"
    assert get_config("bert_large_v4_32").optim.mu_dtype == "bfloat16"


def test_schedule_warmup_then_decay():
    cfg = OptimConfig(
        learning_rate=1e-3, warmup_steps=10, total_steps=110, schedule="cosine"
    )
    sched = make_schedule(cfg)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
    assert float(sched(100)) < 1e-3


def test_optimizer_steps_update_params():
    tx = make_optimizer(
        dataclasses.replace(
            get_config("sst2_bert_base").optim, warmup_steps=0,
            schedule="constant",
        )
    )
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = tx.init(params)
    grads = {"w": jnp.ones((4,), jnp.float32)}
    updates, state = tx.update(grads, state, params)
    new_params = jax.tree.map(lambda p, u: p + u, params, updates)
    assert not np.allclose(np.asarray(new_params["w"]), 1.0)


def test_use_hardware_rng_switches_impl():
    # Run in a subprocess so the global PRNG config doesn't leak into the
    # rest of the suite.
    import subprocess
    import sys

    code = (
        "import jax\n"
        "from tpudl.runtime import use_hardware_rng\n"
        "use_hardware_rng()\n"
        "k = jax.random.key(0)\n"
        "impl = str(jax.random.key_impl(k))\n"
        "assert 'rbg' in impl, impl\n"
        "print('ok')\n"
    )
    import pathlib

    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=str(pathlib.Path(__file__).resolve().parents[1]),
        env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0 and "ok" in out.stdout, out.stderr[-500:]
