"""Test-wide environment: hermetic CPU backend with 8 fake devices.

The distributed test strategy (SURVEY.md §4.2): pjit sharding + collectives
are validated on a fake multi-device CPU mesh via
``--xla_force_host_platform_device_count`` — the substitute for the
reference lineage's "run it on a Databricks cluster" manual testing.
This must run before jax initializes, hence module top-level in conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment may pin a TPU platform via an explicit config update in
# sitecustomize (which beats the env var) — force the hermetic CPU backend.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# TPU-only tests and the environment-failure guard.
#
# Two hermetic-tier rules:
#
# 1. Tests that REQUIRE real TPU hardware (compiled Pallas kernels,
#    hardware-PRNG dropout draws) carry @pytest.mark.tpu and SKIP here
#    with a clear reason instead of failing — they run on the driver's
#    TPU environment.
# 2. The known environment-failure bucket (this CPU jaxlib cannot run
#    cross-process computations — "Multiprocess computations aren't
#    implemented on the CPU backend") is pinned by nodeid below. Any
#    NEW test failing with that signature is flagged loudly at session
#    end: it should either use the spawn-free fake-mesh idiom or carry
#    the marker, not silently grow the bucket.
# ---------------------------------------------------------------------------

_ENV_FAILURE_SIGNATURE = "Multiprocess computations aren't implemented"
#: Non-slow tests known to hit the CPU-jaxlib multiprocess limitation at
#: HEAD (the `slow`-marked spawn tests are deselected from tier-1 and
#: tracked in CHANGES.md PR 4 instead). These now carry
#: @pytest.mark.needs_multiprocess and auto-skip above, so tier-1 runs
#: fully green here — the nodeids stay pinned so a marker accidentally
#: removed surfaces as a KNOWN failure, not a silently NEW one, while
#: any OTHER test failing with the signature is still flagged loudly.
_KNOWN_ENV_FAILURES = frozenset({
    "tests/test_graft_entry.py::test_dryrun_multichip_8",
})
_new_env_failures = []


def _jax_export_available() -> bool:
    """Whether the StableHLO exported path can run at all in this
    environment (tpudl.export.export import-gates jax.export, which
    moves between jax releases)."""
    try:
        from tpudl.export.export import EXPORT_AVAILABLE

        return bool(EXPORT_AVAILABLE)
    except Exception:
        return False


def pytest_collection_modifyitems(config, items):
    # Environment-failure guard, export half: tests (and parity-grid
    # cells) that NEED the exported path carry @pytest.mark.
    # needs_jax_export and auto-skip when jax.export is unavailable —
    # a jax build without it must not error collection of the whole
    # export tier (benchmarks/parity_grid.py applies the same rule to
    # its exported-backend cells via EXPORT_AVAILABLE).
    if not _jax_export_available():
        skip_export = pytest.mark.skip(
            reason="jax.export is unavailable in this jax build; the "
            "exported-artifact path cannot run (compiled-path tests "
            "still cover the engine)"
        )
        for item in items:
            if "needs_jax_export" in item.keywords:
                item.add_marker(skip_export)
    if jax.default_backend() in ("tpu", "axon"):
        return
    skip = pytest.mark.skip(
        reason="requires real TPU hardware (compiled Pallas kernels / "
        "hardware PRNG); the CPU tier runs the interpret-mode parity "
        "suite instead"
    )
    skip_mp = pytest.mark.skip(
        reason="requires a multi-process-capable backend: this CPU "
        "jaxlib cannot compile cross-process computations "
        "('Multiprocess computations aren't implemented on the CPU "
        "backend'); the driver's TPU environment runs it"
    )
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
        if "needs_multiprocess" in item.keywords:
            item.add_marker(skip_mp)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if (
        report.failed
        and call.excinfo is not None
        and _ENV_FAILURE_SIGNATURE in repr(call.excinfo.value)
        and item.nodeid not in _KNOWN_ENV_FAILURES
    ):
        _new_env_failures.append(item.nodeid)


def pytest_terminal_summary(terminalreporter):
    if _new_env_failures:
        terminalreporter.section(
            "NEW environment-limited failures", sep="!"
        )
        terminalreporter.write_line(
            "These tests failed with the known CPU-backend multiprocess "
            "limitation but are NOT in conftest._KNOWN_ENV_FAILURES:"
        )
        for nodeid in _new_env_failures:
            terminalreporter.write_line(f"  {nodeid}")
        terminalreporter.write_line(
            "Do not grow the environment-failure bucket: use the fake "
            "8-device CPU mesh (no process spawn) or mark the test "
            "@pytest.mark.tpu / @pytest.mark.slow."
        )


@pytest.fixture(autouse=True)
def _chaos_env_guard(request):
    """Chaos-marked tests drive env-gated fault injectors
    (TPUDL_SERVE_CHAOS_*): snapshot and restore those knobs around each
    one, so a failing chaos test cannot leak a kill/freeze knob into
    every later engine constructed in this process."""
    if "chaos" not in request.keywords:
        yield
        return
    saved = {
        k: v for k, v in os.environ.items()
        if k.startswith("TPUDL_SERVE_CHAOS_")
    }
    try:
        yield
    finally:
        for k in [
            k for k in os.environ if k.startswith("TPUDL_SERVE_CHAOS_")
        ]:
            del os.environ[k]
        os.environ.update(saved)


@pytest.fixture(scope="session")
def mesh8():
    from tpudl.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(dp=2, fsdp=2, sp=1, tp=2))


@pytest.fixture
def rng_np():
    return np.random.default_rng(0)
