"""Test-wide environment: hermetic CPU backend with 8 fake devices.

The distributed test strategy (SURVEY.md §4.2): pjit sharding + collectives
are validated on a fake multi-device CPU mesh via
``--xla_force_host_platform_device_count`` — the substitute for the
reference lineage's "run it on a Databricks cluster" manual testing.
This must run before jax initializes, hence module top-level in conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment may pin a TPU platform via an explicit config update in
# sitecustomize (which beats the env var) — force the hermetic CPU backend.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from tpudl.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(dp=2, fsdp=2, sp=1, tp=2))


@pytest.fixture
def rng_np():
    return np.random.default_rng(0)
