"""tpudl.obs: span recorder determinism, counters, goodput
classification, the report CLI, runtime instrumentation end-to-end
through fit(), and the distributor's per-worker span merge.

The observability contract under test (ISSUE 1 acceptance): a CPU
synthetic run of >= 20 steps leaves a span JSONL whose report shows the
data-wait / step / compile / checkpoint breakdown, a goodput fraction,
and per-host attribution; the Chrome-trace export is valid trace-event
JSON; and with observability disabled fit() leaves no file behind."""

import json
import os
import threading

import numpy as np
import pytest

import tpudl.obs as obs
from tpudl.obs import counters as obs_counters
from tpudl.obs import goodput as obs_goodput
from tpudl.obs import report as obs_report
from tpudl.obs import spans as obs_spans


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Observability state is process-global; isolate every test."""
    monkeypatch.delenv("TPUDL_OBS_DIR", raising=False)
    obs.disable()
    obs_counters.registry().reset()
    yield
    obs.disable()
    obs_counters.registry().reset()


class FakeClock:
    """Monotonic fake: each call advances by `tick` seconds."""

    def __init__(self, tick=1.0):
        self.t = 0.0
        self.tick = tick

    def __call__(self):
        self.t += self.tick
        return self.t


def _span(cat, ts, dur, host="h", process=0, **kw):
    return {
        "kind": "span", "name": cat, "cat": cat, "ts": float(ts),
        "dur": float(dur), "host": host, "process": process, **kw,
    }


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_and_export_determinism(tmp_path):
    rec = obs_spans.SpanRecorder(clock=FakeClock(), host="h", process=3)
    with rec.span("outer", obs_spans.CAT_STEP, step=0):
        with rec.span("inner", obs_spans.CAT_DATA_WAIT):
            pass
    # Clock ticks: outer enter=1, inner enter=2, inner exit=3, outer
    # exit=4 — the inner span closes (and records) first, fully nested
    # inside the outer one.
    inner, outer = rec.records
    assert (inner["name"], inner["ts"], inner["dur"]) == ("inner", 2.0, 1.0)
    assert (outer["name"], outer["ts"], outer["dur"]) == ("outer", 1.0, 3.0)
    assert outer["step"] == 0
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert all(r["host"] == "h" and r["process"] == 3 for r in rec.records)

    # JSONL round-trip is exact.
    p = rec.export_jsonl(str(tmp_path / "s.jsonl"))
    assert obs_spans.read_jsonl(p) == rec.records

    # Chrome trace export: valid trace-event JSON, microsecond units,
    # one process lane with a metadata row.
    cp = rec.export_chrome_trace(str(tmp_path / "t.json"))
    trace = json.load(open(cp))
    evs = trace["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(meta) == 1 and "h p3" in meta[0]["args"]["name"]
    assert [(e["name"], e["ts"], e["dur"]) for e in xs] == [
        ("inner", 2e6, 1e6), ("outer", 1e6, 3e6),
    ]
    assert xs[1]["args"] == {"step": 0}


def test_streaming_jsonl_and_enable_disable(tmp_path):
    rec = obs.enable(str(tmp_path), clock=FakeClock())
    assert obs_spans.active_recorder() is rec
    rec.record("train_step", obs_spans.CAT_STEP, 1.0, 0.5, {"step": 0})
    rec.event("metrics", cat="metrics", step=1, loss=0.5)
    rec.counters({"counters": {"bytes_ingested": 7}})
    path = rec.path
    obs.disable()
    assert obs_spans.active_recorder() is None
    kinds = [r["kind"] for r in obs_spans.read_jsonl(path)]
    assert kinds == ["span", "event", "counters"]


def test_read_jsonl_tolerates_torn_tail(tmp_path):
    """A worker SIGKILLed mid-flush leaves a partial final line; the
    reader (and so the distributor's failure-path merge) must skip it
    instead of masking the real failure with a JSONDecodeError.
    Corruption ANYWHERE ELSE still raises."""
    p = tmp_path / "s.jsonl"
    good = json.dumps(_span("step", 0, 1))
    p.write_text(good + "\n" + '{"kind": "span", "na')
    assert obs_spans.read_jsonl(str(p)) == [json.loads(good)]
    p.write_text('{"tornemiddle\n' + good + "\n")
    with pytest.raises(json.JSONDecodeError):
        obs_spans.read_jsonl(str(p))


def test_env_var_auto_enables(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUDL_OBS_DIR", str(tmp_path))
    rec = obs_spans.active_recorder()
    assert rec is not None and rec.path.startswith(str(tmp_path))


def test_disabled_span_is_shared_noop():
    s1 = obs.span("x", obs_spans.CAT_STEP)
    s2 = obs.span("y", obs_spans.CAT_COMPILE)
    assert s1 is s2  # one singleton: the disabled path allocates nothing
    with s1:
        pass


def test_recorder_thread_safety():
    rec = obs_spans.SpanRecorder(clock=FakeClock(0.001), host="h", process=0)

    def work():
        for i in range(200):
            rec.record("train_step", obs_spans.CAT_STEP, float(i), 0.1)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rec.records) == 800


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


def test_counters_gauges_histograms():
    reg = obs_counters.Registry()
    reg.counter("bytes").inc(100)
    reg.counter("bytes").inc(50)
    reg.gauge("lr").set(0.1)
    h = reg.histogram("step_time_s")
    for v in [1.0, 2.0, 3.0, 4.0, 100.0]:
        h.observe(v)
    snap = reg.snapshot()
    assert snap["counters"]["bytes"] == 150
    assert snap["gauges"]["lr"] == 0.1
    hs = snap["histograms"]["step_time_s"]
    assert hs["count"] == 5 and hs["min"] == 1.0 and hs["max"] == 100.0
    np.testing.assert_allclose(hs["p50"], 3.0)
    np.testing.assert_allclose(hs["p99"], np.percentile([1, 2, 3, 4, 100], 99))
    with pytest.raises(ValueError, match="monotonic"):
        reg.counter("bytes").inc(-1)
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("bytes")


# ---------------------------------------------------------------------------
# goodput
# ---------------------------------------------------------------------------


def test_goodput_classification_synthetic_timeline():
    # compile [1,6), then 10 x (0.2s data_wait + 0.8s step), then a 1s
    # checkpoint: wall 16s, productive 8s -> goodput 0.5, no idle.
    recs = [_span("compile", 1, 5)]
    t = 6.0
    for i in range(10):
        recs.append(_span("data_wait", t, 0.2))
        recs.append(_span("step", t + 0.2, 0.8))
        t += 1.0
    recs.append(_span("checkpoint", t, 1.0))
    cls = obs_goodput.classify(recs)
    np.testing.assert_allclose(cls["wall_s"], 16.0)
    np.testing.assert_allclose(cls["productive_s"], 8.0)
    np.testing.assert_allclose(cls["compile_s"], 5.0)
    np.testing.assert_allclose(cls["data_wait_s"], 2.0)
    np.testing.assert_allclose(cls["checkpoint_s"], 1.0)
    np.testing.assert_allclose(cls["idle_s"], 0.0, atol=1e-9)
    np.testing.assert_allclose(cls["goodput"], 0.5)
    assert cls["steps"] == 10

    # An uninstrumented gap becomes idle; an unknown category lands in
    # other_s; goodput drops accordingly.
    cls2 = obs_goodput.classify(
        [_span("step", 0, 1), _span("restart", 1, 2), _span("step", 5, 1)]
    )
    np.testing.assert_allclose(cls2["wall_s"], 6.0)
    np.testing.assert_allclose(cls2["other_s"], 2.0)
    np.testing.assert_allclose(cls2["idle_s"], 2.0)
    np.testing.assert_allclose(cls2["goodput"], 2.0 / 6.0)

    # An enclosing worker_run span (same clock, covers everything) only
    # WIDENS the window — summing it would double-count its interior and
    # wipe idle out.
    cls3 = obs_goodput.classify(
        [_span("worker", 0, 10), _span("step", 1, 2)]
    )
    np.testing.assert_allclose(cls3["wall_s"], 10.0)
    np.testing.assert_allclose(cls3["productive_s"], 2.0)
    np.testing.assert_allclose(cls3["other_s"], 0.0)
    np.testing.assert_allclose(cls3["idle_s"], 8.0)

    # Eval steps are useful work with their own bucket.
    cls4 = obs_goodput.classify(
        [_span("step", 0, 1), _span("eval", 1, 1)]
    )
    np.testing.assert_allclose(cls4["eval_s"], 1.0)
    np.testing.assert_allclose(cls4["goodput"], 1.0)
    assert cls4["steps"] == 1  # eval steps don't count as train steps

    assert obs_goodput.classify([])["goodput"] == 0.0


def test_goodput_by_process_aggregates():
    recs = (
        [_span("step", i, 0.5, process=0) for i in range(4)]
        + [_span("step", i, 1.0, process=1) for i in range(4)]
    )
    out = obs_goodput.classify_by_process(recs)
    assert set(out["per_process"]) == {"h/p0", "h/p1"}
    # p0: 2s productive / 3.5s wall; p1: 4s / 4s. Overall sums.
    np.testing.assert_allclose(
        out["overall"]["productive_s"], 6.0
    )
    np.testing.assert_allclose(out["overall"]["wall_s"], 7.5)
    np.testing.assert_allclose(out["overall"]["goodput"], 0.8)
    assert "goodput" in obs_goodput.format_goodput(out["overall"])


def test_goodput_separates_parent_and_worker_with_same_index():
    """A distributor parent and its rank-0 worker share (host, process
    index 0) but run unrelated monotonic clocks — grouping them together
    would compute wall-clock across incomparable epochs. The OS pid
    splits them, and the labels disambiguate."""
    # Parent clock near 100s; worker clock near 1e6s (different epoch).
    recs = (
        [_span("step", 100 + i, 1.0, pid=10) for i in range(3)]
        + [_span("step", 1e6 + i, 1.0, pid=20) for i in range(3)]
    )
    out = obs_goodput.classify_by_process(recs)
    assert set(out["per_process"]) == {"h/p0@10", "h/p0@20"}
    for cls in out["per_process"].values():
        np.testing.assert_allclose(cls["wall_s"], 3.0)
        np.testing.assert_allclose(cls["goodput"], 1.0)
    np.testing.assert_allclose(out["overall"]["wall_s"], 6.0)


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------


def _report_fixture_records():
    """Two hosts: hostA steady 10 ms steps, hostB 20 ms steps (the
    straggler) plus one 150 ms outlier; a compile and a checkpoint."""
    recs = [_span("compile", 0, 2.0, host="hostA")]
    for i in range(20):
        recs.append(_span("data_wait", 2 + i * 0.012, 0.002,
                          host="hostA", step=i))
        recs.append(_span("step", 2.002 + i * 0.012, 0.010,
                          host="hostA", step=i))
    for i in range(20):
        dur = 0.150 if i == 7 else 0.020
        recs.append(_span("step", 2 + i * 0.022, dur,
                          host="hostB", process=1, step=i))
    recs.append(_span("checkpoint", 3.0, 0.5, host="hostA"))
    return recs


def test_report_build_and_straggler_attribution(tmp_path):
    recs = _report_fixture_records()
    rep = obs_report.build_report(recs)
    b = rep["breakdown"]
    assert set(b) >= {"data_wait", "step", "compile", "checkpoint"}
    assert b["step"]["count"] == 40
    assert b["compile"]["count"] == 1
    # hostB mean (26.5 ms) > 1.2x median-of-means -> straggler; hostA not.
    assert rep["per_host"]["hostB/p1"]["straggler"] is True
    assert rep["per_host"]["hostA/p0"]["straggler"] is False
    # The 150 ms step is an outlier (>3x p50), attributed to hostB.
    assert any(
        o["host"] == "hostB" and o["step"] == 7
        for o in rep["outlier_steps"]
    )
    assert 0.0 < rep["goodput"]["overall"]["goodput"] <= 1.0


def test_report_cli_golden(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for r in _report_fixture_records():
            f.write(json.dumps(r) + "\n")
    assert obs_report.main([str(path)]) == 0
    out = capsys.readouterr().out
    # Golden structure: the breakdown table rows, the goodput line, the
    # per-host table with the straggler flagged, and the outlier list.
    for token in ("category", "data_wait", "step", "compile", "checkpoint",
                  "goodput", "host/process", "STRAGGLER", "outlier steps"):
        assert token in out, (token, out)
    assert "hostB/p1" in out
    # Golden step row: 20x10ms + 19x20ms + 1x150ms = 0.73 s total,
    # mean 18.25 ms, p50 15 ms (midpoint of the 10/20 ms halves),
    # p95 20 ms, p99 99.30 ms (interpolating toward the outlier).
    step_row = [l for l in out.splitlines() if l.startswith("step ")][0]
    assert step_row.split() == ["step", "40", "0.73", "18.25", "15.00",
                                "20.00", "99.30"]

    # --json round-trips; --chrome-trace writes valid trace-event JSON.
    trace_out = str(tmp_path / "trace.json")
    assert obs_report.main([str(path), "--json",
                            "--chrome-trace", trace_out]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["breakdown"]["step"]["count"] == 40
    trace = json.load(open(trace_out))
    # Every span re-exported: 1 compile + 20 data_wait + 40 steps + 1
    # checkpoint.
    assert sum(1 for e in trace["traceEvents"] if e.get("ph") == "X") == 62


def test_report_loads_directories(tmp_path):
    d = tmp_path / "obs" / "workers"
    d.mkdir(parents=True)
    with open(tmp_path / "obs" / "a.jsonl", "w") as f:
        f.write(json.dumps(_span("step", 0, 1)) + "\n")
    with open(d / "b.jsonl", "w") as f:
        f.write(json.dumps(_span("step", 1, 1, process=1)) + "\n")
    recs = obs_report.load_records([str(tmp_path / "obs")])
    assert len(recs) == 2  # recursive: workers/ included
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError, match="no .*jsonl"):
        obs_report.load_records([str(empty)])


# ---------------------------------------------------------------------------
# runtime instrumentation end-to-end
# ---------------------------------------------------------------------------


def _tiny_fit_setup():
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.models.resnet import ResNetTiny
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = ResNetTiny(num_classes=4)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.05),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)
    return state, step


def test_fit_observability_end_to_end(tmp_path, capsys):
    """The acceptance path: >= 20 fit steps with obs + checkpointing on,
    then the report CLI over the span dir shows the full breakdown,
    goodput, and per-host table, and the Chrome export is valid."""
    import jax

    from tpudl.checkpoint import CheckpointManager
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.train import fit
    from tpudl.train.logging import MetricLogger

    obs_dir = tmp_path / "obs"
    obs.enable(str(obs_dir))
    state, step = _tiny_fit_setup()
    with CheckpointManager(str(tmp_path / "ckpt")) as mgr:
        state, metrics, info = fit(
            step, state,
            synthetic_classification_batches(
                16, image_shape=(16, 16, 3), num_classes=4, num_batches=22
            ),
            jax.random.key(1),
            log_every=10,
            logger=MetricLogger(),
            checkpoint_manager=mgr,
            checkpoint_every=10,
        )
    assert info["steps"] == 22
    rec = obs_spans.active_recorder()
    records = rec.records
    cats = {r.get("cat") for r in records if r.get("kind") == "span"}
    assert {"step", "compile", "data_wait", "checkpoint"} <= cats
    # 22 calls = 1 compile + 21 steps; every step has a data_wait twin.
    spans = [r for r in records if r.get("kind") == "span"]
    assert sum(1 for s in spans if s["cat"] == "step") == 21
    assert sum(1 for s in spans if s["cat"] == "compile") == 1
    assert sum(1 for s in spans if s["cat"] == "data_wait") == 22
    assert sum(1 for s in spans if s["cat"] == "checkpoint") >= 2
    # MetricLogger fanned metrics into the SAME stream (nested, so user
    # metric names can't collide with reserved record keys); fit
    # appended a counters snapshot with the latency histograms.
    assert any(
        r["kind"] == "event" and r["name"] == "metrics"
        and "loss" in r.get("metrics", {})
        for r in records
    )
    snaps = [r for r in records if r["kind"] == "counters"]
    assert snaps and snaps[-1]["data"]["histograms"]["step_time_s"][
        "count"
    ] == 21
    assert snaps[-1]["data"]["counters"]["checkpoint_saves"] >= 2

    chrome = rec.export_chrome_trace(str(tmp_path / "trace.json"))
    obs.disable()
    trace = json.load(open(chrome))
    assert sum(1 for e in trace["traceEvents"] if e.get("ph") == "X") == len(
        spans
    )

    capsys.readouterr()
    assert obs_report.main([str(obs_dir)]) == 0
    out = capsys.readouterr().out
    for token in ("data_wait", "step", "compile", "checkpoint", "goodput",
                  "host/process"):
        assert token in out, (token, out)


def test_fit_disabled_is_noop(tmp_path, monkeypatch):
    """No recorder, no env var: fit leaves NO span file anywhere and the
    loop takes the uninstrumented branch."""
    import jax

    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.train import fit

    monkeypatch.chdir(tmp_path)
    state, step = _tiny_fit_setup()
    state, metrics, info = fit(
        step, state,
        synthetic_classification_batches(
            16, image_shape=(16, 16, 3), num_classes=4, num_batches=3
        ),
        jax.random.key(1),
    )
    assert info["steps"] == 3
    assert obs_spans.active_recorder() is None
    assert list(tmp_path.rglob("*.jsonl")) == []


def test_evaluate_records_eval_spans(tmp_path):
    import jax

    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        evaluate,
        make_classification_eval_step,
    )

    state, _ = _tiny_fit_setup()
    mesh = make_mesh(MeshSpec(dp=-1))
    eval_step = compile_step(
        make_classification_eval_step(), mesh, state, None,
        donate_state=False, has_rng=False,
    )
    rec = obs.enable(str(tmp_path))
    evaluate(
        eval_step, state,
        synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=4, num_batches=3
        ),
    )
    spans = [r for r in rec.records if r.get("kind") == "span"]
    assert sum(1 for s in spans if s["cat"] == "compile") == 1
    # Eval steps carry their own category so the report's train-step
    # outlier/straggler statistics never mix in eval durations.
    assert sum(1 for s in spans if s["cat"] == "eval") == 2
    assert sum(1 for s in spans if s["cat"] == "step") == 0
    assert sum(1 for s in spans if s["cat"] == "data_wait") == 3


def test_checkpoint_spans(tmp_path):
    import jax.numpy as jnp
    import optax

    from tpudl.checkpoint import restore_train_state, save_train_state
    from tpudl.train.loop import TrainState

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.ones((4,))},
        tx=optax.sgd(0.1),
    )
    rec = obs.enable(str(tmp_path / "obs"))
    save_train_state(str(tmp_path / "ckpt"), state)
    restore_train_state(str(tmp_path / "ckpt"), state)
    names = [
        r["name"] for r in rec.records
        if r.get("cat") == obs_spans.CAT_CHECKPOINT
    ]
    assert names == ["save_train_state", "restore_train_state"]


def test_ingest_spans_and_byte_counters(tmp_path):
    from tpudl.data.ingest import ingest_sst2_tsv

    tsv = tmp_path / "train.tsv"
    sentence = "a fine movie about observability " * 8  # ~264 bytes
    with open(tsv, "w", encoding="utf-8") as f:
        f.write("sentence\tlabel\n")
        for i in range(8):
            f.write(f"{sentence}{i}\t{i % 2}\n")
    rec = obs.enable(str(tmp_path / "obs"))
    ingest_sst2_tsv(str(tsv), str(tmp_path / "out"))
    chunks = [r for r in rec.records if r.get("name") == "ingest_chunk"]
    assert len(chunks) == 1 and chunks[0]["rows"] == 8
    snap = obs_counters.registry().snapshot()
    # Text columns count STRING PAYLOAD bytes (8 x ~264-byte sentences),
    # not 8-byte object pointers — pointer counting would report < 200.
    assert snap["counters"]["bytes_ingested"] > 8 * 200
    assert snap["counters"]["rows_ingested"] == 8


# ---------------------------------------------------------------------------
# distributor merge
# ---------------------------------------------------------------------------


def test_distributor_merges_worker_span_files(tmp_path):
    """run()'s merge step folds per-worker span files (host/process
    tagged) into the parent's stream and removes them, so one report
    sees every rank exactly once."""
    from tpudl.runtime.distributor import TpuDistributor

    rec = obs.enable(str(tmp_path))
    d = TpuDistributor(num_processes=2)
    workers = d._obs_workers_dir()
    assert workers == os.path.join(os.path.dirname(rec.path), "workers")
    os.makedirs(workers)
    for p in range(2):
        with open(os.path.join(workers, f"spans-h-p{p}.jsonl"), "w") as f:
            f.write(json.dumps(
                _span("step", 0, 0.01 * (p + 1), host="wh", process=p)
            ) + "\n")
    d._merge_worker_spans(workers)
    merged = [
        r for r in rec.records
        if r.get("kind") == "span" and r.get("host") == "wh"
    ]
    assert sorted(r["process"] for r in merged) == [0, 1]
    assert not os.path.exists(workers)  # consumed: no double counting


def test_distributor_without_obs_has_no_workers_dir():
    from tpudl.runtime.distributor import TpuDistributor

    assert TpuDistributor(num_processes=2)._obs_workers_dir() is None


@pytest.mark.slow
def test_spawn_merge_and_straggler_report(tmp_path):
    """Real 2-process spawn: each worker streams its own span file (rank
    1 deliberately 10x slower), run() merges, and the report attributes
    the straggler — the cross-host diagnosis path, executed."""
    from tests import dist_helpers
    from tpudl.runtime.distributor import TpuDistributor

    rec = obs.enable(str(tmp_path))
    d = TpuDistributor(num_processes=2, platform="cpu",
                       devices_per_process=1)
    assert d.run(dist_helpers.record_obs_spans) == [0, 1]
    records = rec.records
    step_procs = sorted(
        r["process"] for r in records
        if r.get("cat") == "step" and r.get("step") == 0
    )
    assert step_procs == [0, 1]
    assert any(r.get("name") == "worker_run" for r in records)
    rep = obs_report.build_report(records)
    stragglers = [k for k, v in rep["per_host"].items() if v["straggler"]]
    assert len(stragglers) == 1 and stragglers[0].endswith("/p1")
