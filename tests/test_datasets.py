import numpy as np

from tpudl.data.datasets import (
    materialize_cifar10_like,
    materialize_sst2_like,
    normalize_cifar_batch,
)


def test_cifar10_like_schema(tmp_path):
    conv = materialize_cifar10_like(str(tmp_path / "c10"), num_rows=512)
    assert len(conv) == 512
    batch = next(conv.make_batch_iterator(32, shard_index=0, num_shards=1))
    assert batch["image"].shape == (32, 32, 32, 3)
    assert batch["image"].dtype == np.uint8
    norm = normalize_cifar_batch(batch)
    assert norm["image"].dtype == np.float32
    assert abs(float(norm["image"].mean())) < 1.5


def test_sst2_like_schema(tmp_path):
    conv = materialize_sst2_like(str(tmp_path / "sst2"), num_rows=256, seq_len=64)
    batch = next(conv.make_batch_iterator(16, shard_index=0, num_shards=1))
    assert batch["input_ids"].shape == (16, 64)
    assert batch["attention_mask"].shape == (16, 64)
    assert set(np.unique(batch["label"])) <= {0, 1}
    assert (batch["input_ids"][:, 0] == 101).all()  # [CLS]
    # padding region is zeroed
    masked = batch["input_ids"] * (1 - batch["attention_mask"])
    assert masked.sum() == 0


def test_parquet_to_training_smoke(tmp_path, mesh8):
    """End-to-end L1->L3: Parquet dataset through converter + prefetch into
    the pjit train loop; loss decreases (BASELINE.json configs[2] shape at
    toy scale)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.data.converter import prefetch_to_device
    from tpudl.models.resnet import ResNetTiny
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    conv = materialize_cifar10_like(str(tmp_path / "c10"), num_rows=2048)
    model = ResNetTiny(num_classes=10)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 32, 32, 3)),
        optax.sgd(0.05, momentum=0.9),
    )
    step = compile_step(make_classification_train_step(), mesh8, state, None)
    rng = jax.random.key(1)
    losses = []
    raw = conv.make_batch_iterator(
        64, epochs=2, shuffle=True, shard_index=0, num_shards=1
    )
    batches = (normalize_cifar_batch(b) for b in raw)
    for batch in prefetch_to_device(batches, mesh=mesh8):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert len(losses) == 64  # 2048/64 * 2 epochs
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.9, losses


def test_imagenet_like_pipeline_with_augmenter(tmp_path):
    """configs[2] data contract at reduced scale: 224x224 uint8 Parquet ->
    row-group-streamed converter -> native/numpy augmenter -> f32 batches
    sized for the ResNet-50 input."""
    from tpudl.data.augment import IMAGENET_MEAN, IMAGENET_STD, BatchAugmenter
    from tpudl.data.datasets import materialize_imagenet_like

    conv = materialize_imagenet_like(
        str(tmp_path), num_rows=64, rows_per_file=32, num_classes=10
    )
    aug = BatchAugmenter(
        crop=(224, 224), pad=8, mean=IMAGENET_MEAN, std=IMAGENET_STD, seed=0
    )
    it = conv.make_batch_iterator(
        batch_size=16, shard_index=0, num_shards=1, transform=aug
    )
    batch = next(it)
    assert batch["image"].shape == (16, 224, 224, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].max() < 10
    # Two disjoint shards still cover the 224-row schema.
    a = next(conv.make_batch_iterator(batch_size=8, shard_index=0, num_shards=2))
    b = next(conv.make_batch_iterator(batch_size=8, shard_index=1, num_shards=2))
    assert not np.array_equal(a["image"], b["image"])
