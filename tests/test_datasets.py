import numpy as np

from tpudl.data.datasets import (
    materialize_cifar10_like,
    materialize_sst2_like,
    normalize_cifar_batch,
)


def test_cifar10_like_schema(tmp_path):
    conv = materialize_cifar10_like(str(tmp_path / "c10"), num_rows=512)
    assert len(conv) == 512
    batch = next(conv.make_batch_iterator(32, shard_index=0, num_shards=1))
    assert batch["image"].shape == (32, 32, 32, 3)
    assert batch["image"].dtype == np.uint8
    norm = normalize_cifar_batch(batch)
    assert norm["image"].dtype == np.float32
    assert abs(float(norm["image"].mean())) < 1.5


def test_sst2_like_schema(tmp_path):
    conv = materialize_sst2_like(str(tmp_path / "sst2"), num_rows=256, seq_len=64)
    batch = next(conv.make_batch_iterator(16, shard_index=0, num_shards=1))
    assert batch["input_ids"].shape == (16, 64)
    assert batch["attention_mask"].shape == (16, 64)
    assert set(np.unique(batch["label"])) <= {0, 1}
    assert (batch["input_ids"][:, 0] == 101).all()  # [CLS]
    # padding region is zeroed
    masked = batch["input_ids"] * (1 - batch["attention_mask"])
    assert masked.sum() == 0


def test_parquet_to_training_smoke(tmp_path, mesh8):
    """End-to-end L1->L3: Parquet dataset through converter + prefetch into
    the pjit train loop; loss decreases (BASELINE.json configs[2] shape at
    toy scale)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.data.converter import prefetch_to_device
    from tpudl.models.resnet import ResNetTiny
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    conv = materialize_cifar10_like(str(tmp_path / "c10"), num_rows=2048)
    model = ResNetTiny(num_classes=10)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 32, 32, 3)),
        optax.sgd(0.05, momentum=0.9),
    )
    step = compile_step(make_classification_train_step(), mesh8, state, None)
    rng = jax.random.key(1)
    losses = []
    raw = conv.make_batch_iterator(
        64, epochs=2, shuffle=True, shard_index=0, num_shards=1
    )
    batches = (normalize_cifar_batch(b) for b in raw)
    for batch in prefetch_to_device(batches, mesh=mesh8):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert len(losses) == 64  # 2048/64 * 2 epochs
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.9, losses


def test_imagenet_like_pipeline_with_augmenter(tmp_path):
    """configs[2] data contract at reduced scale: 224x224 uint8 Parquet ->
    row-group-streamed converter -> native/numpy augmenter -> f32 batches
    sized for the ResNet-50 input."""
    from tpudl.data.augment import IMAGENET_MEAN, IMAGENET_STD, BatchAugmenter
    from tpudl.data.datasets import materialize_imagenet_like

    conv = materialize_imagenet_like(
        str(tmp_path), num_rows=64, rows_per_file=32, num_classes=10
    )
    aug = BatchAugmenter(
        crop=(224, 224), pad=8, mean=IMAGENET_MEAN, std=IMAGENET_STD, seed=0
    )
    it = conv.make_batch_iterator(
        batch_size=16, shard_index=0, num_shards=1, transform=aug
    )
    batch = next(it)
    assert batch["image"].shape == (16, 224, 224, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].max() < 10
    # Two disjoint shards still cover the 224-row schema.
    a = next(conv.make_batch_iterator(batch_size=8, shard_index=0, num_shards=2))
    b = next(conv.make_batch_iterator(batch_size=8, shard_index=1, num_shards=2))
    assert not np.array_equal(a["image"], b["image"])


def test_split_train_eval_multifile_holdout(tmp_path):
    """Multi-file datasets hold out the last Parquet file; splits disjoint."""
    from tpudl.data.datasets import split_train_eval
    from tpudl.data.converter import make_converter, write_parquet

    ids = np.arange(512, dtype=np.int64)
    write_parquet(str(tmp_path), {"row_id": ids, "label": ids % 2},
                  rows_per_file=128)
    train, holdout = split_train_eval(make_converter(str(tmp_path)))
    assert len(train.files) == 3 and len(holdout.files) == 1

    def all_ids(conv):
        out = []
        for b in conv.make_batch_iterator(32, shuffle=False, drop_last=False,
                                          shard_index=0, num_shards=1):
            out.extend(b["row_id"].tolist())
        return set(out)

    tr, ev = all_ids(train), all_ids(holdout)
    assert tr.isdisjoint(ev)
    assert tr | ev == set(range(512))


def test_split_train_eval_single_file_auto_splits_rows(tmp_path):
    """A single-file dataset auto-splits rows (round-3 behavior was a
    WARNING + overlapping train/eval — accuracy reported from that path was
    silently train-set accuracy)."""
    from tpudl.data.datasets import split_train_eval
    from tpudl.data.converter import make_converter, write_parquet

    ids = np.arange(200, dtype=np.int64)
    write_parquet(str(tmp_path), {"row_id": ids}, rows_per_file=1024,
                  row_group_size=64)
    train, holdout = split_train_eval(make_converter(str(tmp_path)))
    assert train.num_rows == 180 and holdout.num_rows == 20

    def all_ids(conv, shards=1):
        out = set()
        for s in range(shards):
            for b in conv.make_batch_iterator(
                8, shuffle=False, drop_last=False,
                shard_index=s, num_shards=shards,
            ):
                out.update(b["row_id"].tolist())
        return out

    tr, ev = all_ids(train), all_ids(holdout)
    assert tr == set(range(180))
    assert ev == set(range(180, 200))
    # Row windows stay disjoint under multi-shard reads too, and
    # steps_per_epoch reflects the window.
    tr2, ev2 = all_ids(train, shards=2), all_ids(holdout, shards=2)
    assert tr2.isdisjoint(ev2)
    assert train.steps_per_epoch(8, num_shards=2) == 180 // 2 // 8
    # Shuffled single-file split stays inside its window.
    shuf = set()
    for b in train.make_batch_iterator(8, shuffle=True, seed=3,
                                       shard_index=0, num_shards=1):
        shuf.update(b["row_id"].tolist())
    assert shuf <= set(range(180))


def test_split_train_eval_tiny_dataset_errors(tmp_path):
    import pytest

    from tpudl.data.datasets import split_train_eval
    from tpudl.data.converter import make_converter, write_parquet

    write_parquet(str(tmp_path), {"x": np.arange(1, dtype=np.int64)})
    with pytest.raises(ValueError, match="cannot split"):
        split_train_eval(make_converter(str(tmp_path)))


def test_split_train_eval_guards_and_small_holdout(tmp_path):
    """Review findings: re-splitting a windowed converter is rejected,
    eval_fraction is validated, and a sub-batch holdout still yields one
    (partial) eval batch through eval_stream."""
    import pytest

    from tpudl.data.datasets import eval_stream, split_train_eval
    from tpudl.data.converter import make_converter, write_parquet

    ids = np.arange(200, dtype=np.int64)
    write_parquet(str(tmp_path), {"row_id": ids}, rows_per_file=1024)
    conv = make_converter(str(tmp_path))
    train, holdout = split_train_eval(conv)
    with pytest.raises(ValueError, match="already-windowed"):
        split_train_eval(holdout)
    with pytest.raises(ValueError, match="eval_fraction"):
        split_train_eval(conv, eval_fraction=1.0)
    # holdout has 20 rows < batch 64: partial batch kept, not zero batches
    batches = list(eval_stream(holdout, 64, lambda b: b)())
    assert len(batches) == 1 and len(batches[0]["row_id"]) == 20


def test_eval_stream_batch_divisor_trims_and_skips(tmp_path):
    """batch_divisor (the mesh's dp*fsdp batch-shard count) must trim
    partial batches to a divisible row count and SKIP sub-divisor
    remainders — an indivisible tail batch would fail pjit's
    divisibility check on a sharded mesh."""
    from tpudl.data.datasets import eval_stream
    from tpudl.data.converter import make_converter, write_parquet

    ids = np.arange(22, dtype=np.int64)
    write_parquet(str(tmp_path), {"row_id": ids}, rows_per_file=1024)
    holdout = make_converter(str(tmp_path))

    # A full batch fits: drop_last engages, every batch is already
    # divisible — the divisor changes nothing.
    stream = eval_stream(holdout, 8, lambda b: b, batch_divisor=4)
    assert [len(b["row_id"]) for b in stream()] == [8, 8]
    # Re-iterable (evaluate drains one epoch per call).
    assert [len(b["row_id"]) for b in stream()] == [8, 8]

    # Sub-batch holdout (22 < 64): the partial 22-row batch is kept and
    # TRIMMED down to the divisor multiple.
    assert [
        len(b["row_id"])
        for b in eval_stream(holdout, 64, lambda b: b, batch_divisor=4)()
    ] == [20]
    assert [
        len(b["row_id"])
        for b in eval_stream(holdout, 64, lambda b: b, batch_divisor=8)()
    ] == [16]
    # Divisor larger than the whole holdout: batch skipped entirely
    # (at most divisor-1 rows go unevaluated).
    assert (
        list(eval_stream(holdout, 64, lambda b: b, batch_divisor=32)())
        == []
    )
    # The normalize hook runs on the TRIMMED batch.
    (normed,) = eval_stream(
        holdout, 64, lambda b: dict(b, row_id=b["row_id"] + 1),
        batch_divisor=4,
    )()
    assert normed["row_id"].tolist() == [i + 1 for i in range(20)]


def test_wire_and_device_normalize_match_host_path(tmp_path):
    """wire_cifar_batch + device_normalize_cifar must train on EXACTLY
    the arithmetic of the host normalize_cifar_batch path — same scale/
    bias in f32 — while shipping uint8 over the wire."""
    import jax

    from tpudl.data.datasets import device_normalize_cifar, wire_cifar_batch

    conv = materialize_cifar10_like(str(tmp_path / "c10"), num_rows=128)
    batch = next(conv.make_batch_iterator(32, shard_index=0, num_shards=1))
    wire = wire_cifar_batch(batch)
    assert wire["image"].dtype == np.uint8  # 4x fewer H2D bytes
    assert wire["label"].dtype == np.int32
    on_device = jax.jit(device_normalize_cifar())(wire)
    host = normalize_cifar_batch(batch)
    np.testing.assert_allclose(
        np.asarray(on_device["image"]), host["image"], rtol=0, atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(on_device["label"]), host["label"]
    )
