"""Fused (vocab-streaming) softmax-cross-entropy parity vs the optax
composite, plus the no-[B, V]-softmax materialization guarantee.

Interpreter-mode Pallas on the CPU backend. Shapes deliberately include
non-tile-multiple vocab sizes so the padded columns' exclusion from the
logsumexp / label gather / smoothing sum is under test."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.cross_entropy import (
    softmax_cross_entropy,
    softmax_cross_entropy_ref,
)


def _data(rng, b=19, v=300, scale=3.0):
    logits = jnp.asarray(rng.normal(size=(b, v)) * scale, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, size=(b,)), jnp.int32)
    return logits, labels


@pytest.mark.parametrize("b,v", [(19, 300), (32, 256), (7, 100), (64, 1000)])
def test_forward_parity(rng_np, b, v):
    logits, labels = _data(rng_np, b, v)
    np.testing.assert_allclose(
        np.asarray(softmax_cross_entropy(logits, labels, impl="fused")),
        np.asarray(softmax_cross_entropy_ref(logits, labels)),
        rtol=1e-5, atol=1e-5,
    )


@pytest.mark.parametrize("smoothing", [0.1, 0.3])
def test_forward_parity_label_smoothing(rng_np, smoothing):
    logits, labels = _data(rng_np)
    np.testing.assert_allclose(
        np.asarray(
            softmax_cross_entropy(logits, labels, smoothing, impl="fused")
        ),
        np.asarray(softmax_cross_entropy_ref(logits, labels, smoothing)),
        rtol=1e-5, atol=1e-5,
    )


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_gradient_parity(rng_np, smoothing):
    logits, labels = _data(rng_np)
    gf = jax.grad(
        lambda z: softmax_cross_entropy(
            z, labels, smoothing, impl="fused"
        ).mean()
    )(logits)
    gr = jax.grad(
        lambda z: softmax_cross_entropy_ref(z, labels, smoothing).mean()
    )(logits)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def test_gradient_parity_per_example_cotangent(rng_np):
    """Non-uniform per-example cotangents (the masked-eval weighting
    path) must scale each row's gradient independently."""
    logits, labels = _data(rng_np, b=11, v=200)
    w = jnp.asarray(rng_np.uniform(0.0, 2.0, size=(11,)), jnp.float32)
    gf = jax.grad(
        lambda z: jnp.sum(
            softmax_cross_entropy(z, labels, impl="fused") * w
        )
    )(logits)
    gr = jax.grad(
        lambda z: jnp.sum(softmax_cross_entropy_ref(z, labels) * w)
    )(logits)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def test_vocab_padding_masked_out(rng_np):
    """V=100 pads to 128 lanes; the 28 pad columns must not leak into
    the logsumexp even when the real logits are very negative (a pad
    zero would dominate exp(0))."""
    logits = jnp.asarray(
        rng_np.normal(size=(9, 100)) - 50.0, jnp.float32
    )
    labels = jnp.asarray(rng_np.integers(0, 100, size=(9,)), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(softmax_cross_entropy(logits, labels, impl="fused")),
        np.asarray(softmax_cross_entropy_ref(logits, labels)),
        rtol=1e-5, atol=1e-4,
    )


def test_tiny_num_classes(rng_np):
    """The classification loss sites run V=2 through the same kernel."""
    logits, labels = _data(rng_np, b=33, v=2, scale=1.0)
    np.testing.assert_allclose(
        np.asarray(softmax_cross_entropy(logits, labels, impl="fused")),
        np.asarray(softmax_cross_entropy_ref(logits, labels)),
        rtol=1e-5, atol=1e-5,
    )


def test_auto_cpu_fallback_and_shape_checks(rng_np):
    logits, labels = _data(rng_np)
    auto = softmax_cross_entropy(logits, labels, impl="auto")
    assert (
        np.asarray(auto)
        == np.asarray(softmax_cross_entropy_ref(logits, labels))
    ).all()
    with pytest.raises(ValueError, match="logits"):
        softmax_cross_entropy(logits[None], labels, impl="fused")


def test_lm_shaped_leading_dims(rng_np):
    """[B, S, V] logits / [B, S] labels (the LM loss shape) are
    rank-generic on BOTH paths — fwd and grads — like the optax
    composite always was."""
    logits = jnp.asarray(rng_np.normal(size=(3, 5, 130)) * 2, jnp.float32)
    labels = jnp.asarray(rng_np.integers(0, 130, size=(3, 5)), jnp.int32)
    ref = softmax_cross_entropy_ref(logits, labels)
    for impl in ("reference", "fused"):
        out = softmax_cross_entropy(logits, labels, impl=impl)
        assert out.shape == (3, 5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
    gf = jax.grad(
        lambda z: softmax_cross_entropy(z, labels, impl="fused").mean()
    )(logits)
    gr = jax.grad(lambda z: softmax_cross_entropy_ref(z, labels).mean())(
        logits
    )
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def _sub_jaxprs(params):
    """Sub-jaxprs hiding in an eqn's params (custom_vjp/pjit bodies) —
    hand-rolled so it works across jax versions."""
    from jax.core import ClosedJaxpr, Jaxpr

    for val in params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, Jaxpr):
                yield v


def _bv_eqns(jaxpr, min_size, skip=("pallas_call",)):
    """All equations (recursively, except inside Pallas kernels) whose
    output is a float array of at least ``min_size`` elements."""
    found = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in skip:
                continue
            for sub in _sub_jaxprs(eqn.params):
                walk(sub)
            for var in eqn.outvars:
                aval = var.aval
                if (
                    hasattr(aval, "shape")
                    and np.issubdtype(aval.dtype, np.floating)
                    and int(np.prod(aval.shape or (1,))) >= min_size
                ):
                    found.append((eqn.primitive.name, aval.shape))
    walk(jaxpr)
    return found


def test_fused_never_materializes_bv_softmax(rng_np):
    """Jaxpr audit: with tile-aligned shapes, the fused fwd+bwd contains
    NO [B, V]-sized float intermediate outside the Pallas kernels —
    the probability tensor exists only tile-by-tile in VMEM. The
    composite's jaxpr (sanity leg) contains several."""
    b, v = 64, 256  # tile-aligned: no pad/slice ops in the entry
    logits, labels = _data(rng_np, b, v)

    def fused_loss(z):
        return softmax_cross_entropy(z, labels, impl="fused").mean()

    def ref_loss(z):
        return softmax_cross_entropy_ref(z, labels).mean()

    fwd = jax.make_jaxpr(fused_loss)(logits)
    assert _bv_eqns(fwd.jaxpr, b * v) == [], (
        f"fused forward materializes [B, V] floats: "
        f"{_bv_eqns(fwd.jaxpr, b * v)}"
    )
    # Backward: the gradient itself is [B, V] but must come straight out
    # of the Pallas kernel — nothing else [B, V]-sized around it.
    bwd = jax.make_jaxpr(jax.grad(fused_loss))(logits)
    assert _bv_eqns(bwd.jaxpr, b * v) == [], (
        f"fused backward materializes [B, V] floats beyond the kernel: "
        f"{_bv_eqns(bwd.jaxpr, b * v)}"
    )
    # The audit itself must be able to see a materialization (meta-test).
    assert len(_bv_eqns(jax.make_jaxpr(ref_loss)(logits).jaxpr, b * v)) > 0
