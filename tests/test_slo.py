"""tpudl.obs.slo: burn-rate window math on an injected clock, and the
Engine's SLO-aware admission (ISSUE 6 tentpole piece 3).

The acceptance scenario lives here too: a synthetic overload drives
p99 TTFT past its objective; the monitor fires its shed callback (the
engine sheds queued work as ``shed_slo``) and /healthz reports the
burning objective; recovery — the fast window draining by time —
clears both."""

import json
import urllib.error
import urllib.request

import pytest

import tpudl.obs as obs
from tpudl.obs import counters as obs_counters
from tpudl.obs import exporter as obs_exporter
from tpudl.obs import slo as obs_slo
from tpudl.obs.slo import Objective, SloMonitor


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter.stop_exporter()
    obs_exporter._reset_health_for_tests()
    yield
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter.stop_exporter()
    obs_exporter._reset_health_for_tests()


def _objective(**kw):
    kw.setdefault("name", "ttft_p90")
    kw.setdefault("metric", "serve_ttft_ms")
    kw.setdefault("threshold", 100.0)
    kw.setdefault("quantile", 0.9)
    kw.setdefault("window_s", 100.0)
    kw.setdefault("fast_window_s", 10.0)
    kw.setdefault("min_count", 2)
    return Objective(**kw)


# ---------------------------------------------------------------------------
# Window / burn-rate math
# ---------------------------------------------------------------------------


def test_objective_validation():
    with pytest.raises(ValueError, match="quantile"):
        _objective(quantile=1.0)
    with pytest.raises(ValueError, match="fast_window_s"):
        _objective(fast_window_s=200.0)
    with pytest.raises(ValueError, match="duplicate"):
        SloMonitor([_objective(), _objective()])
    assert _objective(quantile=0.99).budget == pytest.approx(0.01)


def test_burn_rate_arithmetic_exact():
    """Burn rate = violating fraction / error budget, per window. 10
    observations, 3 violating, p90 objective (budget 0.1): burn 3.0."""
    t = [0.0]
    mon = SloMonitor([_objective()], clock=lambda: t[0])
    for i in range(10):
        t[0] += 1.0
        mon.observe("serve_ttft_ms", 500.0 if i < 3 else 50.0)
    state = mon.evaluate()["ttft_p90"]
    for w in ("fast", "slow"):
        assert state[w]["count"] == 10
        assert state[w]["violations"] == 3
        assert state[w]["violation_fraction"] == pytest.approx(0.3)
        assert state[w]["burn_rate"] == pytest.approx(3.0)
    # Both windows >= their burn thresholds (default 1.0) -> burning.
    assert state["burning"] is True


def test_windows_trim_by_time_and_diverge():
    """Observations age out of the fast window first: a past burst
    keeps the slow window hot while the fast window reports clean —
    exactly the state that must NOT alarm (sustained but not current)."""
    t = [0.0]
    mon = SloMonitor([_objective()], clock=lambda: t[0])
    for _ in range(10):
        t[0] += 1.0
        mon.observe("serve_ttft_ms", 500.0)  # all violating, t in [1, 10]
    assert mon.evaluate()["ttft_p90"]["burning"] is True
    # 50s later: fast window (10s) empty, slow window (100s) still
    # holds all 10 violations.
    t[0] = 60.0
    state = mon.evaluate()["ttft_p90"]
    assert state["fast"]["count"] == 0
    assert state["slow"]["violations"] == 10
    assert state["fast"]["burn_rate"] == 0.0
    assert state["slow"]["burn_rate"] == pytest.approx(10.0)
    assert state["burning"] is False  # current-ness gate cleared it
    # 150s: the slow window drains too.
    t[0] = 150.0
    state = mon.evaluate()["ttft_p90"]
    assert state["slow"]["count"] == 0


def test_min_count_suppresses_no_data_alarms():
    t = [0.0]
    mon = SloMonitor([_objective(min_count=5)], clock=lambda: t[0])
    for _ in range(4):
        t[0] += 1.0
        mon.observe("serve_ttft_ms", 1e6)  # violating, but only 4 of them
    state = mon.evaluate()["ttft_p90"]
    assert state["fast"]["burn_rate"] == 0.0
    assert state["burning"] is False
    t[0] += 1.0
    mon.observe("serve_ttft_ms", 1e6)  # the fifth arms it
    assert mon.evaluate()["ttft_p90"]["burning"] is True


def test_transition_callbacks_fire_once_per_edge():
    t = [0.0]
    mon = SloMonitor([_objective()], clock=lambda: t[0])
    edges = []
    mon.subscribe(lambda o, s: edges.append((o.name, s["burning"])))
    for _ in range(5):
        t[0] += 0.5
        mon.observe("serve_ttft_ms", 500.0)
    for _ in range(3):
        mon.evaluate()  # steady state: no repeated firing
    assert edges == [("ttft_p90", True)]
    t[0] += 200.0
    mon.evaluate()
    assert edges == [("ttft_p90", True), ("ttft_p90", False)]
    # And health() reflects the cleared state.
    assert mon.health()["healthy"] is True
    assert mon.health()["burning"] == []


def test_count_cap_eviction_keeps_violation_count_consistent(monkeypatch):
    monkeypatch.setattr(obs_slo, "MAX_WINDOW_OBS", 8)
    t = [0.0]
    mon = SloMonitor([_objective()], clock=lambda: t[0])
    # 8 violations fill the cap, then 8 clean observations evict them
    # one by one — the running violation count must follow.
    for _ in range(8):
        mon.observe("serve_ttft_ms", 500.0)
    for _ in range(8):
        mon.observe("serve_ttft_ms", 1.0)
    state = mon.evaluate()["ttft_p90"]
    assert state["fast"]["count"] == 8
    assert state["fast"]["violations"] == 0
    assert state["burning"] is False


def test_unwatched_metric_is_ignored():
    mon = SloMonitor([_objective()])
    mon.observe("something_else_ms", 1e9)
    assert mon.evaluate()["ttft_p90"]["fast"]["count"] == 0
    assert mon.watched_metrics() == ["serve_ttft_ms"]


# ---------------------------------------------------------------------------
# The acceptance scenario: synthetic overload through the real engine
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

    cfg = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def test_engine_sheds_on_burn_and_recovers(tiny_model, tmp_path):
    """Overload pushes TTFT far past the objective -> the monitor
    fires, the engine sheds its queue as shed_slo, /healthz goes 503
    naming the burning objective; once the windows drain, admission
    serves again and /healthz recovers."""
    from tpudl.serve import Request, ServeSession

    model, params = tiny_model
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mon = SloMonitor(
        [_objective(window_s=1000.0, fast_window_s=100.0, min_count=2)],
        clock=clock,
    )
    fired = []
    mon.subscribe(lambda o, s: fired.append((o.name, s["burning"])))
    session = ServeSession.from_model(
        model, params, prompt_len=8, num_slots=2, clock=clock, slo=mon,
    )
    ex = obs_exporter.start_exporter(port=0)
    url = f"http://127.0.0.1:{ex.port}/healthz"

    # Six requests submitted at t=0; the "overload" is 500 virtual
    # seconds of queue delay before the engine gets to them.
    for i in range(6):
        session.submit(Request(f"r{i}", [1, 2, 3], max_new_tokens=2))
    t[0] = 500.0
    results = session.collect()

    # The first seats blew the objective (TTFT ~500s >> 100ms), the
    # monitor fired, and the engine shed the remaining queue.
    assert fired and fired[0] == ("ttft_p90", True)
    served = [r for r in results.values() if r.ok]
    shed = [r for r in results.values() if r.finish_reason == "shed_slo"]
    assert served and shed
    assert len(served) + len(shed) == 6
    assert (
        obs_counters.registry().counter("serve_requests_shed_slo").value
        == len(shed)
    )

    # /healthz: 503, the burning objective named by both the slo source
    # and the engine's own view.
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url, timeout=10.0)
    assert ei.value.code == 503
    body = json.load(ei.value)
    assert body["sources"]["slo"]["burning"] == ["ttft_p90"]
    assert body["sources"]["serve_engine"]["slo_burning"] == ["ttft_p90"]

    # Recovery: the windows drain by time alone; the probe clears...
    t[0] += 5000.0
    status = urllib.request.urlopen(url, timeout=10.0).status
    assert status == 200
    assert fired[-1] == ("ttft_p90", False)

    # ...and admission serves again: fresh requests at low TTFT
    # complete, nothing shed, still healthy.
    for i in range(2):
        session.submit(Request(f"ok{i}", [1, 2, 3], max_new_tokens=2))
    results = session.collect()
    assert all(r.ok for r in results.values())
    assert urllib.request.urlopen(url, timeout=10.0).status == 200
