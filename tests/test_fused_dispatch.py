"""Fused multi-step dispatch (compile_step(steps_per_dispatch=K) +
fit + window-mode prefetch), async metric drain, overlap bucketing,
donation audit, and the persistent compile cache.

Parity contract: fit(steps_per_dispatch=K) is BIT-FOR-BIT identical to
K single dispatches — same final params, opt state, rng key, and
per-step losses — asserted exactly on a matmul (no-dropout) model.
XLA schedules the fused-scan and straight-line programs independently,
so conv/dropout models may show float-reassociation-level divergence
(the same caveat class test_accumulation documents); the contract suite
pins the exact case.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.data.prefetch import prefetch_to_device
from tpudl.obs import counters as obs_counters
from tpudl.obs import spans as obs_spans
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train import loop as loop_mod
from tpudl.train.loop import (
    compile_step,
    create_train_state,
    fit,
    make_classification_eval_step,
    make_classification_train_step,
)
from tpudl.train.metrics import MetricFetcher


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    from tpudl.obs import spans as obs

    monkeypatch.delenv("TPUDL_OBS_DIR", raising=False)
    monkeypatch.delenv("TPUDL_OVERLAP_BUCKET_MB", raising=False)
    obs.disable()
    obs_counters.registry().reset()
    yield
    obs.disable()
    obs_counters.registry().reset()


def _bert_state(lr=1e-3, seed=0):
    from tpudl.models.bert import BertConfig, BertForSequenceClassification

    cfg = BertConfig(
        vocab_size=256, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, hidden_dropout=0.0, attention_dropout=0.0,
        dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    return create_train_state(
        jax.random.key(seed), model, jnp.zeros((1, 16), jnp.int32),
        optax.adamw(lr),
    )


def _token_batches(n, batch=16, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "input_ids": rng.integers(0, 256, (batch, seq)).astype(np.int32),
            "attention_mask": np.ones((batch, seq), np.int32),
            "label": rng.integers(0, 2, (batch,)).astype(np.int32),
        }
        for _ in range(n)
    ]


def _train_step():
    return make_classification_train_step(
        input_keys=("input_ids", "attention_mask"), label_key="label"
    )


def _tree_equal(a, b):
    return all(
        jax.tree.leaves(
            jax.tree.map(
                lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()),
                a, b,
            )
        )
    )


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------


def test_fused_dispatch_bitwise_parity():
    """fit(steps_per_dispatch=4) over 8 batches == steps_per_dispatch=1
    bit-for-bit: final params, opt state, rng key, per-step losses."""
    mesh = make_mesh(MeshSpec(dp=-1))
    batches = _token_batches(8)
    rng = jax.random.key(1)

    results = {}
    for k in (1, 4):
        state = _bert_state()
        step = compile_step(
            _train_step(), mesh, state, None, donate_state=False,
            steps_per_dispatch=k,
        )
        losses = []
        state, metrics, info = fit(
            step, state, list(batches), rng, log_every=1,
            logger=lambda i, m, ls=losses: ls.append(m["loss"]),
        )
        results[k] = (state, metrics, losses, info)

    s1, m1, l1, i1 = results[1]
    s4, m4, l4, i4 = results[4]
    assert l1 == l4  # exact float equality, all 8 steps
    assert m1 == m4
    assert _tree_equal(s1.params, s4.params)
    assert _tree_equal(s1.opt_state, s4.opt_state)
    assert int(s1.step) == int(s4.step) == 8
    # the rng key is never consumed destructively by either path
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(rng)),
        np.asarray(jax.random.key_data(jax.random.key(1))),
    )
    assert i1["dispatches"] == 8 and i4["dispatches"] == 2
    assert i4["steps"] == 8 and i4["steps_per_dispatch"] == 4


def test_fused_dispatch_ragged_tail_falls_back_to_single():
    """10 batches at K=4: 2 fused windows + 2 single-step dispatches,
    result identical to 10 single dispatches."""
    mesh = make_mesh(MeshSpec(dp=-1))
    batches = _token_batches(10)
    rng = jax.random.key(1)

    state_ref = _bert_state()
    step_ref = compile_step(
        _train_step(), mesh, state_ref, None, donate_state=False
    )
    state_ref, _, _ = fit(step_ref, state_ref, list(batches), rng)

    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    state, _, info = fit(step, state, list(batches), rng)
    assert info["steps"] == 10
    assert info["dispatches"] == 4  # 2 windows + 2 tail singles
    assert _tree_equal(state_ref.params, state.params)


def test_fused_dispatch_respects_num_steps():
    """num_steps not divisible by K: windows run while K steps remain,
    the remainder runs single-step, and exactly num_steps execute."""
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    state, _, info = fit(
        step, state, _token_batches(12), jax.random.key(1), num_steps=6
    )
    assert info["steps"] == 6
    assert info["dispatches"] == 3  # 1 window + 2 singles
    assert int(state.step) == 6


def test_fit_rejects_mismatched_steps_per_dispatch():
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(_train_step(), mesh, state, None, donate_state=False)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        fit(step, state, _token_batches(4), jax.random.key(1),
            steps_per_dispatch=4)


def test_compile_step_rejects_fused_eval():
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    with pytest.raises(ValueError, match="has_rng"):
        compile_step(
            make_classification_eval_step(), mesh, state, None,
            has_rng=False, steps_per_dispatch=4,
        )


# ---------------------------------------------------------------------------
# window-mode prefetcher
# ---------------------------------------------------------------------------


def test_prefetcher_window_mode_feeds_fused_fit():
    """prefetch_to_device(window=K) assembles [K, B, ...] windows
    host-side; fit consumes them via pull_window and the result matches
    the single-dispatch reference exactly (including the ragged tail)."""
    mesh = make_mesh(MeshSpec(dp=-1))
    batches = _token_batches(10)
    rng = jax.random.key(1)

    state_ref = _bert_state()
    step_ref = compile_step(
        _train_step(), mesh, state_ref, None, donate_state=False
    )
    state_ref, _, _ = fit(step_ref, state_ref, list(batches), rng)

    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    with prefetch_to_device(iter(batches), mesh=mesh, window=4) as pf:
        assert pf.window == 4
        state, _, info = fit(step, state, pf, rng)
    assert info["steps"] == 10
    assert _tree_equal(state_ref.params, state.params)


def test_prefetcher_pull_window_protocol():
    """pull_window returns stacked windows in source order, then None
    once only the ragged tail remains; iteration drains the tail."""
    batches = [{"x": np.full((4, 2), i, np.float32)} for i in range(7)]
    with prefetch_to_device(iter(batches), window=3) as pf:
        w1 = pf.pull_window()
        np.testing.assert_array_equal(
            np.asarray(w1["x"])[:, 0, 0], [0, 1, 2]
        )
        assert np.asarray(w1["x"]).shape == (3, 4, 2)
        w2 = pf.pull_window(3)
        np.testing.assert_array_equal(
            np.asarray(w2["x"])[:, 0, 0], [3, 4, 5]
        )
        assert pf.pull_window() is None  # tail single held back
        tail = list(pf)
        assert [int(np.asarray(b["x"])[0, 0]) for b in tail] == [6]
        with pytest.raises(ValueError, match="window"):
            pf.pull_window(2)


def test_prefetcher_window_plain_iteration_unstacks():
    """Iterating a window-mode prefetcher without pull_window still
    yields the exact single-batch sequence (lazy unstack fallback)."""
    batches = [{"x": np.full((2,), i, np.float32)} for i in range(5)]
    with prefetch_to_device(iter(batches), window=2) as pf:
        seen = [float(np.asarray(b["x"])[0]) for b in pf]
    assert seen == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_prefetcher_window_shape_break_flushes_singles():
    """A smaller partial batch landing INSIDE a would-be window (not
    just at the stream end) must not crash the stack — the group
    flushes as singles and every batch still arrives, in order."""
    sizes = [4, 4, 4, 3, 4, 4]
    batches = [
        {"x": np.full((n, 2), i, np.float32)}
        for i, n in enumerate(sizes)
    ]
    with prefetch_to_device(iter(batches), window=2) as pf:
        w1 = pf.pull_window()
        np.testing.assert_array_equal(np.asarray(w1["x"])[:, 0, 0], [0, 1])
        # Batch 3 (size 3) breaks group [2]; from here the consumer is
        # in single-batch mode and drains everything in source order.
        assert pf.pull_window() is None
        rest = [
            (int(np.asarray(b["x"])[0, 0]), np.asarray(b["x"]).shape[0])
            for b in pf
        ]
    assert rest == [(2, 4), (3, 3), (4, 4), (5, 4)]


def test_fit_rejects_prefetcher_window_mismatch():
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    with prefetch_to_device(iter(_token_batches(8)), window=2) as pf:
        with pytest.raises(ValueError, match="window"):
            fit(step, state, pf, jax.random.key(1))


# ---------------------------------------------------------------------------
# async metric drain
# ---------------------------------------------------------------------------


def test_async_metrics_no_sync_fetch_per_logged_step(monkeypatch):
    """With the async drain on, fit() performs ZERO synchronous metric
    fetches per logged step in the steady state (the acceptance
    criterion): every host conversion happens on the fetcher thread,
    and every logger callback still fires, in order, before return."""
    calls = []
    real = loop_mod._to_host_metrics
    monkeypatch.setattr(
        loop_mod, "_to_host_metrics",
        lambda m: calls.append(1) or real(m),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    logged = []
    state, metrics, info = fit(
        step, state, _token_batches(8), jax.random.key(1),
        log_every=1, logger=lambda i, m: logged.append((i, m["loss"])),
    )
    assert calls == []  # no synchronous fetch, steady state or final
    assert [i for i, _ in logged] == list(range(1, 9))
    assert metrics is not None and metrics["loss"] == logged[-1][1]

    # Control: the sync path fetches once per logged step.
    state2 = _bert_state()
    step2 = compile_step(
        _train_step(), mesh, state2, None, donate_state=False
    )
    fit(step2, state2, _token_batches(4), jax.random.key(1),
        log_every=1, logger=lambda i, m: None, async_metrics=False)
    assert len(calls) >= 4


def test_async_metrics_on_single_step_path():
    """async_metrics=True works with steps_per_dispatch=1 too."""
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(_train_step(), mesh, state, None, donate_state=False)
    logged = []
    state, metrics, _ = fit(
        step, state, _token_batches(4), jax.random.key(1),
        log_every=2, logger=lambda i, m: logged.append(i),
        async_metrics=True,
    )
    assert logged == [2, 4]
    assert set(metrics) == {"loss", "accuracy"}


def test_metric_fetcher_roundtrip_and_order():
    with MetricFetcher(window=2) as f:
        f.submit(1, {"loss": np.float32(0.5)}, 1)
        f.submit(2, {"loss": np.arange(3, dtype=np.float32)}, 3)
        out = f.flush()
    assert [s for s, _ in out] == [1, 2, 3, 4]
    assert out[1][1]["loss"] == 0.0 and out[3][1]["loss"] == 2.0


def test_metric_fetcher_backpressure_and_errors():
    import threading
    import time as _time

    gate = threading.Event()

    class Slow:
        def __array__(self, dtype=None):
            gate.wait(5.0)
            return np.array(1.0)

    f = MetricFetcher(window=1)
    assert f.submit(1, {"loss": Slow()}, 1) == 0.0
    timer = threading.Timer(0.2, gate.set)
    timer.start()
    t0 = _time.perf_counter()
    waited = f.submit(2, {"loss": np.float32(2.0)}, 1)
    assert waited > 0.05  # blocked on the window until the gate opened
    assert _time.perf_counter() - t0 > 0.05
    out = f.flush()
    assert [s for s, _ in out] == [1, 2]
    f.close()

    class Boom:
        def __array__(self, dtype=None):
            raise RuntimeError("metric readback exploded")

    f2 = MetricFetcher(window=4)
    f2.submit(1, {"loss": Boom()}, 1)
    with pytest.raises(RuntimeError, match="exploded"):
        for _ in range(50):
            _time.sleep(0.01)
            f2.flush()
    # Sticky: the error keeps raising on every later call instead of
    # being consumed once (a cleared error let a later flush() wait
    # forever on work the dead worker would never finish).
    with pytest.raises(RuntimeError, match="exploded"):
        f2.flush()
    f2.close()

    # Deadlock regression: a worker error with MORE dispatches still
    # outstanding must abandon them — flush() raises promptly instead
    # of hanging on pending work no thread will ever convert. The gate
    # guarantees dispatches 2 and 3 are queued behind the failing one.
    gate2 = threading.Event()

    class GatedBoom:
        def __array__(self, dtype=None):
            gate2.wait(5.0)
            raise RuntimeError("exploded late")

    f3 = MetricFetcher(window=8)
    f3.submit(1, {"loss": GatedBoom()}, 1)
    f3.submit(2, {"loss": np.float32(1.0)}, 1)
    f3.submit(3, {"loss": np.float32(2.0)}, 1)
    gate2.set()
    t0 = _time.perf_counter()
    with pytest.raises(RuntimeError, match="exploded late"):
        f3.flush()
    assert _time.perf_counter() - t0 < 3.0
    f3.close()


def test_fused_fit_records_dispatch_and_metric_spans(tmp_path):
    """The obs stream of a fused run carries dispatch_window spans whose
    window attr makes goodput count K steps each, and the end-of-fit
    flush records metric_wait separately from data_wait."""
    from tpudl.obs import goodput as obs_goodput

    rec = obs_spans.enable(str(tmp_path / "obs"))
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    fit(step, state, _token_batches(8), jax.random.key(1), log_every=1,
        logger=lambda i, m: None)
    records = rec.records
    obs_spans.disable()
    windows = [
        r for r in records
        if r.get("kind") == "span" and r.get("name") == "dispatch_window"
    ]
    assert len(windows) == 1  # first window classifies as compile
    assert windows[0]["window"] == 4
    cls = obs_goodput.classify(records)
    assert cls["steps"] == 4  # 1 span, window-weighted
    assert "metric_wait_s" in cls
    compile_spans = [
        r for r in records
        if r.get("kind") == "span" and r.get("cat") == "compile"
    ]
    assert compile_spans and compile_spans[0].get("window") == 4


# ---------------------------------------------------------------------------
# ft interaction: checkpoint / preemption at window granularity
# ---------------------------------------------------------------------------


def test_fused_checkpoint_window_granularity_and_resume(tmp_path):
    """Checkpoints commit at dispatch-window ends keyed by the true step
    counter, and a fused resume is schedule-identical to the
    uninterrupted fused run (losses bit-equal across the boundary)."""
    from tpudl.ft.data import ResumableIterator
    from tpudl.ft.manager import AsyncCheckpointManager
    from tpudl.ft.supervisor import resume_run

    mesh = make_mesh(MeshSpec(dp=-1))
    rng = jax.random.key(42)
    total = 8
    batches = _token_batches(total)

    def build_step(state, k):
        return compile_step(
            _train_step(), mesh, state, None, donate_state=False,
            steps_per_dispatch=k,
        )

    # Uninterrupted fused control.
    state = _bert_state()
    control = []
    fit(build_step(state, 4), state, ResumableIterator(iter(batches)),
        rng, num_steps=total, log_every=1,
        logger=lambda i, m: control.append(m["loss"]))

    # Interrupted: cadence 3 with K=4 -> saves land at window ends 4, 8
    # (crossed cadence steps commit at the window's final step).
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        state = _bert_state()
        head = []
        fit(build_step(state, 4), state,
            ResumableIterator(iter(batches)), rng, num_steps=4,
            log_every=1, logger=lambda i, m: head.append(m["loss"]),
            checkpoint_manager=mgr, checkpoint_every=3)
        assert mgr.latest_step() == 4  # window end, not cadence step 3

    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr2:
        template = _bert_state(seed=5)
        state, r_rng, rbatches, start = resume_run(
            mgr2, template, ResumableIterator(iter(batches))
        )
        assert start == 4
        tail = []
        fit(build_step(state, 4), state, rbatches, r_rng,
            num_steps=total - start, log_every=1,
            logger=lambda i, m: tail.append(m["loss"]))
    assert head == control[:4]
    assert tail == control[4:]


def test_fused_preemption_stops_at_window_boundary():
    """A preemption flag raised mid-window stops the loop at the NEXT
    window boundary: steps stay a multiple of K and the run reports
    preempted."""
    from tpudl.ft import preemption as ft_preemption

    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, donate_state=False,
        steps_per_dispatch=4,
    )
    batches = _token_batches(16)

    def feed():
        for j, b in enumerate(batches):
            if j == 5:  # mid window 2: delivered, then the flag is seen
                os.kill(os.getpid(), signal.SIGTERM)
            yield b

    with ft_preemption.PreemptionGuard(grace_s=60.0):
        state, _, info = fit(step, state, feed(), jax.random.key(1))
        assert ft_preemption.requested()
    assert info["preempted"] is True
    assert info["steps"] == 8  # window 2 completes; window 3 never starts


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


def test_donation_audit_single_and_fused():
    """Train-mode compile_step AND the fused K-step program donate the
    state buffers: every old state leaf is deleted after the call, and
    the output state reuses the donated buffers (pointer identity on
    CPU) rather than silently copying. Eval steps must NOT donate.

    Runs through the generalized tpudl.analysis.donation audit (this
    test's original inline check, promoted to a reusable helper)."""
    from tpudl.analysis.donation import audit_donation

    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state()
    step = compile_step(
        _train_step(), mesh, state, None, steps_per_dispatch=4
    )
    state = jax.device_put(state, step.state_shardings)
    batch = _token_batches(1)[0]
    rng = jax.random.key(1)

    # Most buffers must be reused in place, not copied: min_reuse=0.8
    # allows a few small leaves (step counter, scalars) elsewhere.
    (state2, _), report = audit_donation(
        step, (state, batch, rng), donate_argnums=(0,)
    )
    assert report.ok, report.describe()

    window = {k: np.stack([batch[k]] * 4) for k in batch}
    (state3, stacked), report2 = audit_donation(
        step.window_step, (state2, window, rng), donate_argnums=(0,)
    )
    assert report2.ok, (
        f"fused program: {report2.describe()} (donation lost across "
        f"the scan carry)"
    )
    assert np.asarray(stacked["loss"]).shape == (4,)

    # Eval never donates: the caller's state survives repeated use.
    eval_step = compile_step(
        make_classification_eval_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh, state3, None, has_rng=False,
    )
    eval_step(state3, batch)
    assert not any(leaf.is_deleted() for leaf in jax.tree.leaves(state3))
    eval_step(state3, batch)


# ---------------------------------------------------------------------------
# overlap bucketing
# ---------------------------------------------------------------------------


def test_overlap_bucket_assignment():
    from tpudl.parallel import overlap

    leaves = [np.zeros((256,), np.float32) for _ in range(4)]  # 1 KiB each
    buckets = overlap.bucket_assignment(leaves, 2048)
    assert buckets == [[0, 1], [2, 3]]
    # An oversized leaf gets its own bucket, never split.
    leaves = [
        np.zeros((64,), np.float32),
        np.zeros((4096,), np.float32),
        np.zeros((64,), np.float32),
    ]
    buckets = overlap.bucket_assignment(leaves, 1024)
    assert buckets == [[0], [1], [2]]
    with pytest.raises(ValueError):
        overlap.bucket_assignment(leaves, 0)


def test_overlap_accumulate_is_identity_on_values():
    from tpudl.parallel import overlap

    rng = np.random.default_rng(0)
    acc = {"a": rng.normal(size=(128,)).astype(np.float32),
           "b": {"c": rng.normal(size=(64, 3)).astype(np.float32)}}
    new = jax.tree.map(lambda x: x * 0.5, acc)
    plain = jax.tree.map(np.add, acc, new)
    bucketed = jax.jit(
        lambda a, b: overlap.accumulate(a, b, bucket_bytes=256)
    )(acc, new)
    for p, q in zip(jax.tree.leaves(plain), jax.tree.leaves(bucketed)):
        np.testing.assert_array_equal(p, np.asarray(q))


def test_accum_step_with_overlap_buckets_matches_plain(mesh8, tmp_path):
    """accum_steps=2 with tiny buckets forced on == the plain
    accumulated step bit-for-bit (barriers are identity), and tracing
    the bucketed step sets the overlap_buckets gauge."""
    batch = _token_batches(1, batch=32)[0]
    rng = jax.random.key(1)

    def run(bucket_mb):
        state = _bert_state()
        step = compile_step(
            make_classification_train_step(
                input_keys=("input_ids", "attention_mask"),
                label_key="label", accum_steps=2,
                overlap_bucket_mb=bucket_mb,
            ),
            mesh8, state, None, donate_state=False,
        )
        new_state, metrics = step(state, batch, rng)
        return new_state, metrics

    rec = obs_spans.enable(str(tmp_path / "obs"))
    s_bucketed, m_bucketed = run(0.001)  # ~1 KiB buckets: many of them
    gauge = obs_counters.registry().gauge("overlap_buckets").value
    obs_spans.disable()
    assert gauge > 1, "bucketed trace must record the bucket count"
    s_plain, m_plain = run(None)  # auto default (4 MiB ~= one bucket here)
    assert float(m_plain["loss"]) == float(m_bucketed["loss"])
    assert _tree_equal(s_plain.params, s_bucketed.params)
    assert rec is not None


def test_overlap_env_knob(monkeypatch):
    from tpudl.parallel import overlap

    monkeypatch.setenv("TPUDL_OVERLAP_BUCKET_MB", "2")
    assert overlap.bucket_bytes_from_env() == 2 << 20
    monkeypatch.setenv("TPUDL_OVERLAP_BUCKET_MB", "0")
    assert overlap.bucket_bytes_from_env() is None
    # 0 disables even with an explicit request at the accumulate level.
    acc = {"a": np.ones((8,), np.float32)}
    out = overlap.accumulate(acc, acc)
    np.testing.assert_array_equal(np.asarray(out["a"]), 2.0)


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------


def test_compile_cache_second_compile_records_hit(tmp_path, monkeypatch):
    """With TPUDL_COMPILE_CACHE set, a second compile_step of the same
    signature is served from the persistent cache and the obs stream
    records the hit."""
    from tpudl.runtime import compile_cache

    monkeypatch.setenv("TPUDL_COMPILE_CACHE", str(tmp_path / "cache"))
    defaults = {
        "jax_compilation_cache_dir": None,
        "jax_persistent_cache_min_compile_time_secs": 1.0,
        "jax_persistent_cache_min_entry_size_bytes": 0,
    }
    assert compile_cache.enable_compile_cache()
    try:
        rec = obs_spans.enable(str(tmp_path / "obs"))
        mesh = make_mesh(MeshSpec(dp=-1))
        batch = _token_batches(1)[0]
        rng = jax.random.key(1)

        def compile_and_step():
            state = _bert_state()
            step = compile_step(
                _train_step(), mesh, state, None, donate_state=False
            )
            step(state, batch, rng)

        reg = obs_counters.registry()
        compile_and_step()  # cold cache: this compile writes the entry
        hits_before = reg.counter("compile_cache_hits").value
        compile_and_step()  # same signature, fresh jit -> persistent hit
        assert reg.counter("compile_cache_hits").value > hits_before
        events = [
            r for r in rec.records
            if r.get("kind") == "event" and r["name"] == "compile_cache_hit"
        ]
        assert events, "cache hit must land in the span stream"
    finally:
        obs_spans.disable()
        for k, v in defaults.items():
            jax.config.update(k, v)
        try:
            from jax._src import compilation_cache as _jax_cc

            _jax_cc.reset_cache()  # un-latch: later tests stay uncached
        except Exception:
            pass


def test_enable_compile_cache_noop_without_knob(monkeypatch):
    from tpudl.runtime import compile_cache

    monkeypatch.delenv("TPUDL_COMPILE_CACHE", raising=False)
    assert compile_cache.enable_compile_cache() is False
