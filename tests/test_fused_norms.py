"""Fused LayerNorm/RMSNorm kernel parity vs the XLA composites.

Runs the actual Pallas kernels in interpreter mode on the CPU backend
(the hermetic tier — same code compiles on TPU). Coverage: fwd + grads,
with/without the fused residual add, odd (non-tile-multiple) shapes,
bf16-compute tolerance, and the summed-output cotangent path (the
pre-norm residual carry)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.norms import (
    layer_norm,
    layer_norm_ref,
    rms_norm,
    rms_norm_ref,
)


def _arrs(rng, n=37, h=100, dtype=jnp.float32):
    x = jnp.asarray(rng.normal(size=(n, h)), dtype)
    r = jnp.asarray(rng.normal(size=(n, h)), dtype)
    scale = jnp.asarray(rng.normal(size=(h,)) * 0.5 + 1.0, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(h,)) * 0.1, jnp.float32)
    return x, r, scale, bias


@pytest.mark.parametrize("n,h", [(37, 100), (16, 128), (130, 257)])
def test_layer_norm_forward_parity(rng_np, n, h):
    x, r, scale, bias = _arrs(rng_np, n, h)
    out = layer_norm(x, scale, bias, impl="fused")
    ref = layer_norm_ref(x, scale, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_layer_norm_residual_forward_parity(rng_np):
    x, r, scale, bias = _arrs(rng_np)
    y, s = layer_norm(x, scale, bias, r, impl="fused")
    yr, sr = layer_norm_ref(x, scale, bias, r)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr),
                               rtol=1e-6, atol=1e-6)


def test_layer_norm_return_sum_false(rng_np):
    """The post-norm form (BERT) skips the summed output but must norm
    the same value."""
    x, r, scale, bias = _arrs(rng_np)
    y = layer_norm(x, scale, bias, r, return_sum=False, impl="fused")
    yr, _ = layer_norm_ref(x, scale, bias, r)
    assert not isinstance(y, tuple)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)


def test_layer_norm_gradient_parity(rng_np):
    x, r, scale, bias = _arrs(rng_np)

    def loss(fn):
        def f(x, scale, bias, r):
            y, s = fn(x, scale, bias, r)
            # Use BOTH outputs so the summed-output cotangent (gs) path
            # is exercised, with different weights to catch a swap.
            return jnp.sum(y * y) + jnp.sum(jnp.sin(s))
        return f

    gf = jax.grad(loss(lambda *a: layer_norm(*a, impl="fused")),
                  argnums=(0, 1, 2, 3))(x, scale, bias, r)
    gr = jax.grad(loss(lambda *a: layer_norm_ref(*a)),
                  argnums=(0, 1, 2, 3))(x, scale, bias, r)
    for name, a, b in zip(("dx", "dscale", "dbias", "dres"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"{name} mismatch",
        )


def test_layer_norm_gradient_parity_no_residual(rng_np):
    x, _, scale, bias = _arrs(rng_np, n=24, h=96)

    def mk(fn):
        return lambda x, s, b: jnp.sum(fn(x, s, b) ** 2)

    gf = jax.grad(mk(lambda *a: layer_norm(*a, impl="fused")),
                  argnums=(0, 1, 2))(x, scale, bias)
    gr = jax.grad(mk(lambda *a: layer_norm_ref(*a)),
                  argnums=(0, 1, 2))(x, scale, bias)
    for name, a, b in zip(("dx", "dscale", "dbias"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"{name} mismatch",
        )


@pytest.mark.parametrize("n,h", [(37, 100), (16, 128), (64, 384)])
def test_rms_norm_forward_parity(rng_np, n, h):
    x, r, scale, _ = _arrs(rng_np, n, h)
    out = rms_norm(x, scale, impl="fused")
    ref = rms_norm_ref(x, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_rms_norm_residual_gradient_parity(rng_np):
    x, r, scale, _ = _arrs(rng_np)

    def loss(fn):
        def f(x, scale, r):
            y, s = fn(x, scale, r)
            return jnp.sum(y * y) + jnp.sum(jnp.sin(s))
        return f

    gf = jax.grad(loss(lambda *a: rms_norm(*a, impl="fused")),
                  argnums=(0, 1, 2))(x, scale, r)
    gr = jax.grad(loss(lambda *a: rms_norm_ref(*a)),
                  argnums=(0, 1, 2))(x, scale, r)
    for name, a, b in zip(("dx", "dscale", "dres"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"{name} mismatch",
        )


def test_rms_norm_gradient_parity_no_residual(rng_np):
    x, _, scale, _ = _arrs(rng_np, n=24, h=96)
    gf = jax.grad(
        lambda x, s: jnp.sum(rms_norm(x, s, impl="fused") ** 2),
        argnums=(0, 1),
    )(x, scale)
    gr = jax.grad(
        lambda x, s: jnp.sum(rms_norm_ref(x, s) ** 2), argnums=(0, 1)
    )(x, scale)
    for name, a, b in zip(("dx", "dscale"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"{name} mismatch",
        )


def test_bf16_compute_tolerance(rng_np):
    """bf16 activations: fused keeps f32 statistics like the composite;
    outputs agree at bf16 resolution and keep the input dtype."""
    x, r, scale, bias = _arrs(rng_np, dtype=jnp.bfloat16)
    y, s = layer_norm(x, scale, bias, r, impl="fused")
    yr, sr = layer_norm_ref(x, scale, bias, r)
    assert y.dtype == jnp.bfloat16 and s.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0.05, atol=0.05,
    )
    z = rms_norm(x, scale, impl="fused")
    zr = rms_norm_ref(x, scale)
    assert z.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(z, np.float32), np.asarray(zr, np.float32),
        rtol=0.05, atol=0.05,
    )


def test_3d_inputs_and_auto_cpu_fallback(rng_np):
    """[B, S, H] inputs flatten/unflatten transparently, and impl='auto'
    off-TPU is BITWISE the reference composite (the model-flag fallback
    contract)."""
    x = jnp.asarray(rng_np.normal(size=(2, 9, 100)), jnp.float32)
    scale = jnp.ones((100,))
    bias = jnp.zeros((100,))
    fused = layer_norm(x, scale, bias, impl="fused")
    assert fused.shape == x.shape
    auto = layer_norm(x, scale, bias, impl="auto")
    ref = layer_norm_ref(x, scale, bias)
    assert (np.asarray(auto) == np.asarray(ref)).all()


def test_bad_impl_rejected(rng_np):
    x = jnp.ones((8, 32))
    with pytest.raises(ValueError, match="impl"):
        rms_norm(x, jnp.ones((32,)), impl="pallas")
