"""Every BASELINE.json configs[i] entry is drivable from a CLI one-liner
(SURVEY.md §5.6: "one config file per configs[i] entry" — made
load-bearing: the round-3 gap was configs[2]/[3] hardcoded out of reach).

Each test launches the real workload script as a subprocess on the fake
8-device CPU mesh (TPUDL_PLATFORM=cpu + host-device-count XLA flag — the
notebooks' apply_platform_env hook), at toy step counts. Big models
override to tiny shapes via the SAME CLI the full run uses; the config's
mesh / strategy / schema / accumulation path is what's exercised.
"""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]

ENV = {
    **os.environ,
    "TPUDL_PLATFORM": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "JAX_PLATFORMS": "cpu",
}


def _run(script, *argv, timeout=600):
    out = subprocess.run(
        [sys.executable, str(REPO / script), *argv],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=str(REPO),
        env=ENV,
    )
    assert out.returncode == 0, (
        f"{script} {' '.join(argv)} failed:\n{out.stdout[-2000:]}\n"
        f"{out.stderr[-2000:]}"
    )
    return out.stdout


# configs[0]: ResNet-18 / CIFAR-10 smoke.
def test_configs0_cifar10_resnet18_cli():
    out = _run(
        "notebooks/cv/train_cifar10.py",
        "--config", "cifar10_resnet18",
        "--steps", "4", "--batch", "32", "--eval-steps", "1",
    )
    assert "cifar10_resnet18: resnet18" in out
    assert "held-out eval" in out


# configs[1]: BERT-base SST-2 fine-tune (tiny model via the same CLI).
def test_configs1_sst2_bert_base_cli():
    out = _run(
        "notebooks/nlp/train_sst2.py",
        "--config", "sst2_bert_base",
        "--model", "bert-tiny", "--steps", "4", "--batch", "32",
        "--eval-steps", "1",
    )
    assert "sst2_bert_base: bert-tiny" in out
    assert "held-out eval" in out


# configs[2]: ResNet-50 / ImageNet DP — declared batch 1024 realized via
# gradient accumulation; tiny batch here, real 224x224 schema + augmenter.
def test_configs2_imagenet_resnet50_cli(tmp_path):
    out = _run(
        "notebooks/cv/train_cifar10.py",
        "--config", "imagenet_resnet50_dp",
        "--steps", "3", "--batch", "16", "--accum", "2",
        "--eval-steps", "1",
        "--data-dir", str(tmp_path / "im"), "--materialize",
        "--rows", "128",
        # ResNet-50 fwd+bwd inside the accumulation scan is a heavy CPU
        # compile; generous ceiling so host contention can't flake it.
        timeout=1800,
    )
    assert "imagenet_resnet50_dp: resnet50" in out
    assert "(accum 2)" in out
    assert "held-out eval" in out


# configs[3]: BERT-large v4-32 fine-tune — fsdp mesh clamps to the fake
# 8-device mesh (fsdp=4 x dp=2), accumulation path on.
def test_configs3_bert_large_cli():
    out = _run(
        "notebooks/nlp/train_sst2.py",
        "--config", "bert_large_v4_32",
        "--model", "bert-tiny", "--steps", "4", "--batch", "64",
        "--accum", "2", "--eval-steps", "1",
    )
    assert "bert_large_v4_32: bert-tiny" in out
    assert "'fsdp': 4" in out  # the declared mesh actually clamped+used
    assert "strategy fsdp" in out
    assert "held-out eval" in out


# configs[4]: Llama LoRA (tiny model via the same CLI).
def test_configs4_llama_lora_cli():
    out = _run(
        "notebooks/nlp/finetune_lora.py",
        "--model", "llama-tiny-lora", "--steps", "4", "--batch", "16",
        "--mesh", "2,2,1,2",
    )
    assert "llama-tiny-lora" in out
    assert "trainable" in out


@pytest.mark.parametrize(
    "spec,devices,expect",
    [
        ((-1, 4, 1, 1, 1, 1), 1, (1, 1, 1, 1, 1, 1)),
        ((-1, 4, 1, 1, 1, 1), 8, (2, 4, 1, 1, 1, 1)),
        ((-1, 8, 1, 2, 1, 1), 8, (1, 8, 1, 1, 1, 1)),
        ((-1, 1, 1, 1, 1, 1), 8, (8, 1, 1, 1, 1, 1)),
    ],
)
def test_meshspec_fit(spec, devices, expect):
    from tpudl.runtime import MeshSpec

    fitted = MeshSpec(*spec).fit(devices)
    assert fitted.resolve(devices) == expect


def test_meshspec_fit_requires_wildcard():
    from tpudl.runtime import MeshSpec

    with pytest.raises(ValueError, match="wildcard"):
        MeshSpec(2, 2, 1, 1, 1, 1).fit(4)


# configs[4] raw-text vertical: TSV -> byte-level BPE -> ids -> LoRA
# fine-tune, one command.
def test_configs4_text_data_bpe_vertical(tmp_path):
    tsv = tmp_path / "train.tsv"
    with open(tsv, "w", encoding="utf-8") as f:
        f.write("sentence\tlabel\n")
        for i in range(256):
            s = ("a wonderful charming movie" if i % 2
                 else "a dull dreadful film")
            f.write(f"{s}\t{i % 2}\n")
    out = _run(
        "notebooks/nlp/finetune_lora.py",
        "--model", "llama-tiny-lora", "--steps", "4", "--batch", "16",
        "--seq-len", "32",
        "--text-data", "--ingest", str(tsv),
        "--data-dir", str(tmp_path / "data"),
    )
    assert "trained byte-level BPE" in out
    assert "ingested" in out
    # reuse path: second run skips ingestion/tokenization
    out2 = _run(
        "notebooks/nlp/finetune_lora.py",
        "--model", "llama-tiny-lora", "--steps", "2", "--batch", "16",
        "--seq-len", "32",
        "--text-data", "--data-dir", str(tmp_path / "data"),
    )
    assert "reusing tokenized dataset" in out2
