"""Flash-attention kernel parity vs the reference einsum implementation.

Runs the actual Pallas kernel in interpreter mode on the CPU backend
(SURVEY.md §4.2's hermetic tier); the same code compiles for TPU. Parity
bar follows the reference's cross-backend contract (reference
notebooks/cv/onnx_experiments.py:142-144): explicit rtol/atol, forward and
backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.attention import (
    attend,
    causal_mask,
    dot_product_attention,
    padding_mask,
)
from tpudl.ops.flash_attention import flash_attention


def _qkv(rng, b=2, sq=128, skv=128, h=2, d=64, dtype=jnp.float32):
    shape = (b, sq, h, d)
    kshape = (b, skv, h, d)
    q = jnp.asarray(rng.normal(size=shape), dtype)
    k = jnp.asarray(rng.normal(size=kshape), dtype)
    v = jnp.asarray(rng.normal(size=kshape), dtype)
    return q, k, v


def _padding(rng, b, skv):
    lengths = rng.integers(skv // 2, skv + 1, size=(b,))
    return (np.arange(skv)[None, :] < lengths[:, None]).astype(np.int32)


def test_forward_parity_no_mask(rng_np):
    q, k, v = _qkv(rng_np)
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_forward_parity_padding_mask(rng_np):
    q, k, v = _qkv(rng_np, sq=64, skv=64)
    mask2d = jnp.asarray(_padding(rng_np, 2, 64))
    ref = dot_product_attention(q, k, v, mask=padding_mask(mask2d))
    out = flash_attention(q, k, v, mask=padding_mask(mask2d), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_forward_parity_causal(rng_np):
    q, k, v = _qkv(rng_np, sq=128, skv=128)
    ref = dot_product_attention(q, k, v, mask=causal_mask(128, 128))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_forward_parity_causal_unequal_lens(rng_np):
    """Causal with Sq != Skv must be bottom-right aligned like
    causal_mask (decode-style: short q window over a long kv history)."""
    q, k, v = _qkv(rng_np, sq=64, skv=192)
    ref = dot_product_attention(q, k, v, mask=causal_mask(64, 192))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)

    def ref_loss(q, k, v):
        return jnp.sum(dot_product_attention(
            q, k, v, mask=causal_mask(64, 192)) ** 2)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    fl_grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for name, rg, fg in zip("qkv", ref_grads, fl_grads):
        np.testing.assert_allclose(
            np.asarray(fg), np.asarray(rg), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_forward_unaligned_seq_lens(rng_np):
    """Sq/Skv not multiples of the tile size exercise the padding path."""
    q, k, v = _qkv(rng_np, sq=50, skv=70)
    mask2d = jnp.asarray(_padding(rng_np, 2, 70))
    ref = dot_product_attention(q, k, v, mask=padding_mask(mask2d))
    out = flash_attention(q, k, v, mask=padding_mask(mask2d), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_gradient_parity(rng_np):
    q, k, v = _qkv(rng_np, sq=64, skv=64)
    mask2d = jnp.asarray(_padding(rng_np, 2, 64))

    def ref_loss(q, k, v):
        out = dot_product_attention(q, k, v, mask=padding_mask(mask2d))
        return jnp.sum(out * out)

    def flash_loss(q, k, v):
        out = flash_attention(q, k, v, mask=padding_mask(mask2d),
                              interpret=True)
        return jnp.sum(out * out)

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    fl_grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for name, rg, fg in zip("qkv", ref_grads, fl_grads):
        np.testing.assert_allclose(
            np.asarray(fg), np.asarray(rg), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_gradient_parity_causal(rng_np):
    q, k, v = _qkv(rng_np, sq=64, skv=64)

    def ref_loss(q, k, v):
        return jnp.sum(dot_product_attention(
            q, k, v, mask=causal_mask(64, 64)) ** 2)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    fl_grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for name, rg, fg in zip("qkv", ref_grads, fl_grads):
        np.testing.assert_allclose(
            np.asarray(fg), np.asarray(rg), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_bf16_inputs(rng_np):
    q, k, v = _qkv(rng_np, dtype=jnp.bfloat16)
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.02,
    )


def test_attend_dispatch_flash(rng_np):
    q, k, v = _qkv(rng_np, sq=32, skv=32)
    out = attend(q, k, v, implementation="flash")
    ref = attend(q, k, v, implementation="reference")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_dense_mask_rejected(rng_np):
    q, k, v = _qkv(rng_np, sq=16, skv=16)
    dense = jnp.ones((2, 2, 16, 16), bool)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, mask=dense, interpret=True)


def test_flash_dropout_contract(rng_np):
    """Flash supports in-kernel dropout on TPU (round-4; the S>512
    carve-out is gone); interpret mode has no hardware PRNG so the CPU
    test asserts the informative refusal, and real-TPU behavior is
    verified by scripts/tpu_dropout_check.py."""
    q, k, v = _qkv(rng_np, sq=16, skv=16)
    with pytest.raises(NotImplementedError, match="hardware PRNG"):
        attend(q, k, v, implementation="flash", dropout_rate=0.1,
               dropout_rng=jax.random.key(0))
    # A nonzero rate with no rng must also be rejected, not silently dropped.
    with pytest.raises(ValueError, match="dropout"):
        attend(q, k, v, implementation="flash", dropout_rate=0.1)
