"""Trace-summary tool (tpudl.train.profiling) against a synthetic trace
in the exact plugins/profile layout jax.profiler.trace writes, plus an
end-to-end capture through fit()'s profiling hook on the CPU backend."""

import gzip
import json
import os

import numpy as np

from tpudl.train.profiling import format_summary, summarize_trace


def _write_trace(tmp_path, events):
    run = tmp_path / "plugins" / "profile" / "2026_07_31"
    run.mkdir(parents=True)
    path = run / "host.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def _meta(pid, name):
    return {"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}


def _op(pid, tid, name, dur_us, cat, flops=0, bytes_=0):
    return {
        "ph": "X", "pid": pid, "tid": tid, "ts": 0.0, "dur": dur_us,
        "name": name,
        "args": {
            "hlo_category": cat,
            "model_flops": str(flops),
            "bytes_accessed": str(bytes_),
        },
    }


def test_summarize_synthetic_trace(tmp_path):
    events = [
        _meta(3, "/device:TPU:0"),
        _meta(7, "/host:CPU"),
        # op stream (tid 3): 2 matmuls + 1 pointwise, over 2 steps
        _op(3, 3, "fusion.1", 1000.0, "convolution fusion",
            flops=100e9, bytes_=50e6),
        _op(3, 3, "fusion.1", 1000.0, "convolution fusion",
            flops=100e9, bytes_=50e6),
        _op(3, 3, "fusion.2", 500.0, "loop fusion", bytes_=400e6),
        _op(3, 3, "fusion.2", 500.0, "loop fusion", bytes_=400e6),
        # aggregate launch span on another tid must be ignored
        _op(3, 1, "jit_step", 3000.0, "?"),
        # host events must be ignored
        _op(7, 1, "python", 9999.0, "?"),
    ]
    root = _write_trace(tmp_path, events)
    s = summarize_trace(root, steps=2)
    assert s["num_events"] == 4
    np.testing.assert_allclose(s["total_ms_per_step"], 1.5)
    conv = s["by_category"]["convolution fusion"]
    np.testing.assert_allclose(conv["ms_per_step"], 1.0)
    np.testing.assert_allclose(conv["share"], 2.0 / 3.0)
    # 200 GFLOP over 2000 us = 100 TF/s
    np.testing.assert_allclose(conv["tflops"], 100.0)
    lf = s["by_category"]["loop fusion"]
    np.testing.assert_allclose(lf["gbps"], 800.0)  # 800 MB / 1000 us
    assert s["top_ops"][0]["name"] == "fusion.1"
    txt = format_summary(s)
    assert "convolution fusion" in txt and "fusion.1" in txt


def test_op_stream_prefers_hlo_category_tid(tmp_path):
    """Regression (ADVICE round 5): a launch/annotation thread with MORE
    events than the HLO-op thread must not be selected as the op stream
    — tids whose events carry args.hlo_category win; most-events is only
    the fallback when no thread carries the field."""
    launch = {
        "ph": "X", "pid": 3, "tid": 1, "ts": 0.0, "dur": 10.0,
        "name": "launch", "args": {},
    }
    events = [
        _meta(3, "/device:TPU:0"),
        # op stream (tid 3): only 2 events, but they carry hlo_category
        _op(3, 3, "fusion.1", 1000.0, "convolution fusion", flops=1e9),
        _op(3, 3, "fusion.2", 500.0, "loop fusion"),
    ] + [dict(launch, ts=float(i)) for i in range(10)]  # noisier tid 1
    root = _write_trace(tmp_path, events)
    s = summarize_trace(root)
    assert s["num_events"] == 2
    np.testing.assert_allclose(s["total_ms_per_step"], 1.5)
    assert set(s["by_category"]) == {"convolution fusion", "loop fusion"}

    # Fallback: no thread carries hlo_category -> most-events wins.
    bare = [_meta(3, "/device:TPU:0")] + [
        dict(launch, ts=float(i)) for i in range(3)
    ]
    s2 = summarize_trace(_write_trace(tmp_path / "bare", bare))
    assert s2["num_events"] == 3


def test_fit_profile_hook_roundtrip(tmp_path):
    """fit(profile_dir=...) -> summarize_trace on the CPU backend: the
    whole capture-to-analysis loop works without TensorBoard."""
    import jax
    import jax.numpy as jnp
    import optax
    import pytest

    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.models.resnet import ResNetTiny
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        fit,
        make_classification_train_step,
    )

    model = ResNetTiny(num_classes=4)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.05),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)
    prof = str(tmp_path / "prof")
    fit(
        step, state,
        synthetic_classification_batches(
            16, image_shape=(16, 16, 3), num_classes=4, num_batches=6
        ),
        jax.random.key(1),
        profile_dir=prof, profile_window=(2, 5),
    )
    try:
        s = summarize_trace(prof, steps=3, device_substr="cpu")
    except (FileNotFoundError, ValueError) as e:  # pragma: no cover
        pytest.skip(f"CPU trace lacks device events here: {e}")
    assert s["total_ms_per_step"] > 0
    assert s["by_category"]
