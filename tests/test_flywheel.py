"""tpudl.flywheel: per-tenant continual LoRA refresh from live traffic
(ISSUE 18).

The contract under test: the request log's schema-v2 OPTIONAL sample
fields round-trip (and v1/sample-less records are skipped loudly, not
fatally); the declarative SampleFilter admits by first-match rules +
bounds + dedup; the RefreshTrainer trains ONLY the tenant's factors,
checkpoints factors + log position, and resumes a preempted refresh
schedule-identical (bitwise factors vs the uninterrupted control); the
FlywheelController never swaps under a lease (refusal -> pending ->
retry); and the whole loop — serve under load -> durable log ->
filter -> refresh -> hot-swap — measurably changes served outputs with
zero recompiles in the serving steady state.
"""

import json
import os
import signal
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.analysis.dispatch import assert_no_recompiles
from tpudl.flywheel import (
    FlywheelController,
    RefreshTrainer,
    SampleFilter,
    SampleStream,
    example_from_record,
    pack_examples,
)
from tpudl.ft import preemption as ft_preemption
from tpudl.ft.manager import AsyncCheckpointManager
from tpudl.models.llama import LlamaConfig, LlamaForCausalLM
from tpudl.models.lora import extract_adapters, merge_adapter
from tpudl.obs import counters as obs_counters
from tpudl.obs import metering, requestlog
from tpudl.serve import Request, ServeSession

#: Tiny on purpose: every session/trainer here compiles on CPU.
TINY = dict(
    vocab_size=128,
    hidden_size=32,
    num_layers=1,
    num_heads=2,
    num_kv_heads=1,
    intermediate_size=64,
    max_seq_len=64,
    rope_theta=10_000.0,
    dtype=jnp.float32,
)
PROMPT_LEN = 8


@pytest.fixture(autouse=True)
def _clean_flywheel(monkeypatch):
    """Writer + meter + registry are process-global; isolate every
    test (the test_requestlog idiom)."""
    monkeypatch.delenv("TPUDL_OBS_DIR", raising=False)
    monkeypatch.delenv("TPUDL_OBS_REQUEST_LOG", raising=False)
    monkeypatch.delenv("TPUDL_OBS_REQUEST_LOG_SAMPLES", raising=False)
    requestlog.disable()
    requestlog.set_samples_capture(None)
    metering.meter().reset()
    obs_counters.registry().reset()
    ft_preemption.reset()
    yield
    requestlog.disable()
    requestlog.set_samples_capture(None)
    metering.meter().reset()
    obs_counters.registry().reset()
    ft_preemption.reset()


@pytest.fixture(scope="module")
def base():
    cfg = LlamaConfig(**TINY)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return cfg, model, params


def make_adapter(base, seed: int, rank: int = 2, b_scale: float = 0.05):
    cfg, _, _ = base
    import dataclasses

    lp = LlamaForCausalLM(
        dataclasses.replace(cfg, lora_rank=rank)
    ).init(
        jax.random.key(seed), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    flat = extract_adapters(lp)
    rng = np.random.default_rng(seed)
    return {
        path: {
            "lora_a": np.asarray(f["lora_a"]),
            "lora_b": rng.normal(
                scale=b_scale, size=np.shape(f["lora_b"])
            ).astype(np.float32),
        }
        for path, f in flat.items()
    }


@pytest.fixture(scope="module")
def trainer(base):
    """One compiled RefreshTrainer for the whole module — the
    production shape (compile once, refresh many tenants/rounds)."""
    cfg, _, params = base
    return RefreshTrainer(
        cfg, params, rank=2, alpha=16.0, batch_size=2, seq_len=16,
        learning_rate=0.1, precision="bf16", epochs=2,
    )


def _rec(i, tenant=None, finish="eos", prompt=None, output=None, **kw):
    kw.setdefault("tokens_in", 3)
    kw.setdefault("tokens_out", 4)
    kw.setdefault("ts", float(i))
    return requestlog.build_record(
        f"r{i}", finish, tenant=tenant,
        prompt_ids=prompt, output_ids=output, **kw,
    )


def _examples(n, tenant="t0", seed=0, out_len=4):
    rng = np.random.default_rng(seed)
    return [
        {
            "tenant": tenant,
            "prompt_ids": rng.integers(1, 100, size=5).tolist(),
            "output_ids": rng.integers(1, 100, size=out_len).tolist(),
        }
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# schema v2: round-trip + v1 compat
# ---------------------------------------------------------------------------


def test_samples_capture_override(monkeypatch):
    """set_samples_capture beats the env knob in both directions and
    None hands control back to it (the no-os.environ bench surface)."""
    assert not requestlog.samples_enabled()
    requestlog.set_samples_capture(True)
    try:
        assert requestlog.samples_enabled()
        monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG_SAMPLES", "0")
        assert requestlog.samples_enabled()
        requestlog.set_samples_capture(False)
        monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG_SAMPLES", "1")
        assert not requestlog.samples_enabled()
    finally:
        requestlog.set_samples_capture(None)
    assert requestlog.samples_enabled()


def test_schema_v2_sample_roundtrip(tmp_path):
    """v2 records carry prompt_ids/output_ids through the durable log
    byte-exactly; records built without samples carry NEITHER key
    (byte-shaped like v1 plus the version stamp)."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d)
    w.log(_rec(0, tenant="t0", prompt=[5, 6, 7], output=[9, 10]))
    w.log(_rec(1, tenant="t0"))
    w.close()
    got = list(requestlog.read_request_log(d))
    assert len(got) == 2
    assert got[0]["v"] == requestlog.SCHEMA_VERSION == 2
    assert got[0]["prompt_ids"] == [5, 6, 7]
    assert got[0]["output_ids"] == [9, 10]
    assert "prompt_ids" not in got[1] and "output_ids" not in got[1]


def test_v1_records_still_read_and_meter(tmp_path):
    """The version contract, consumer half: a segment of v1 records
    (no sample fields) reads fine and the meter folds them — only the
    flywheel filter skips them (loudly, tested below)."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d)
    for i in range(3):
        r = _rec(i, tenant="t1")
        r["v"] = 1
        w.log(r)
    w.close()
    got = list(requestlog.read_request_log(d))
    assert [r["v"] for r in got] == [1, 1, 1]
    m = metering.TenantMeter()
    for r in got:
        m.ingest(r)
    assert m.tenants()["t1"]["requests_completed"] == 3


def test_engine_captures_samples_only_when_enabled(
    base, monkeypatch, tmp_path
):
    """The engine._finish capture: with the knob off, completed
    records carry no token ids; with it on, prompt_ids/output_ids
    match the request's actual prompt and served completion."""
    _, model, params = base
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
    )
    requestlog.enable(str(tmp_path / "off"))
    out = session.serve(
        [Request("a", [3, 4, 5], max_new_tokens=4)]
    )
    requestlog.disable()
    rec = next(iter(requestlog.read_request_log(str(tmp_path / "off"))))
    assert "prompt_ids" not in rec and "output_ids" not in rec

    monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG_SAMPLES", "1")
    requestlog.enable(str(tmp_path / "on"))
    out = session.serve(
        [Request("b", [3, 4, 5], max_new_tokens=4)]
    )
    requestlog.disable()
    rec = next(iter(requestlog.read_request_log(str(tmp_path / "on"))))
    assert rec["prompt_ids"] == [3, 4, 5]
    assert rec["output_ids"] == list(out["b"].tokens)
    assert rec["finish_reason"] in ("eos", "length")


# ---------------------------------------------------------------------------
# SampleFilter: rules, bounds, dedup, v1 skip
# ---------------------------------------------------------------------------


def test_filter_first_match_rules():
    """tpudl.rules shape: ordered (pattern, verdict) against
    '{tenant}/{finish_reason}', first match wins, default covers the
    rest; None tenant matches as '-'."""
    f = SampleFilter(
        rules=(
            (r"^-/", "drop"),
            (r"^bad/", "drop"),
            (r"/eos$", "keep"),
            (r"/length$", "drop"),
        ),
        default="drop",
    )
    keep = _rec(0, tenant="good", prompt=[1, 2], output=[3, 4])
    assert f.admit(keep) is not None
    # First match wins: bad/eos hits the tenant deny before /eos keep.
    bad = _rec(1, tenant="bad", prompt=[1, 2], output=[3, 4])
    assert f.admit(bad) is None
    trunc = _rec(
        2, tenant="good", finish="length", prompt=[1, 2], output=[5, 6]
    )
    assert f.admit(trunc) is None
    # None tenant matches as the literal '-' (base traffic): the ^-/
    # deny wins over the later /eos$ keep — first match, again.
    anon = _rec(3, prompt=[1, 2], output=[3, 4])
    assert f.admit(anon) is None
    # Unmatched path falls to the explicit default.
    other = _rec(
        4, tenant="good", finish="shed_capacity",
        prompt=[1, 2], output=[3, 4],
    )
    assert f.admit(other) is None
    s = f.stats()
    assert s["admitted"] == 1 and s["dropped_rule"] == 4

    with pytest.raises(ValueError, match="verdict"):
        SampleFilter(rules=((r"x", "maybe"),))
    with pytest.raises(ValueError, match="default"):
        SampleFilter(default="both")


def test_filter_bounds_and_dedup():
    f = SampleFilter(
        min_output_tokens=2, max_output_tokens=4, dedup_prefix=3
    )
    assert f.admit(_rec(0, tenant="t", prompt=[1], output=[2])) is None
    assert f.admit(
        _rec(1, tenant="t", prompt=[1], output=[2] * 5)
    ) is None
    first = _rec(2, tenant="t", prompt=[7, 8, 9, 1], output=[3, 4])
    assert f.admit(first) is not None
    # Same 3-token prompt prefix, different tail: a duplicate.
    dup = _rec(3, tenant="t", prompt=[7, 8, 9, 2], output=[5, 6])
    assert f.admit(dup) is None
    # Same prefix, DIFFERENT tenant: not a duplicate (dedup is
    # per-tenant — tenants don't shadow each other's traffic).
    other = _rec(4, tenant="u", prompt=[7, 8, 9, 1], output=[3, 4])
    assert f.admit(other) is not None
    s = f.stats()
    assert s["dropped_bounds"] == 2 and s["dropped_duplicate"] == 1
    assert s["admitted"] == 2
    f.reset_dedup()
    assert f.admit(
        _rec(5, tenant="t", prompt=[7, 8, 9, 3], output=[1, 2])
    ) is not None


def test_filter_skips_sample_less_records_loudly():
    """v1 records (and v2 written with capture off) are SKIPPED with
    one RuntimeWarning per filter + a counted stat — never an error
    (old segments stay consumable)."""
    f = SampleFilter()
    v1 = _rec(0, tenant="t")
    v1["v"] = 1
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert f.admit(v1) is None
        assert f.admit(_rec(1, tenant="t")) is None  # v2, capture off
    hits = [w for w in caught if issubclass(w.category, RuntimeWarning)]
    assert len(hits) == 1, "exactly one warning per filter instance"
    assert "dropped_no_sample" in str(hits[0].message)
    assert f.stats()["dropped_no_sample"] == 2


def test_pack_examples_fixed_shapes():
    """Every batch has the SAME [B, L] shape (ragged tail padded with
    mask-0 rows); mask covers exactly the surviving output positions;
    long prompts right-truncate from the left."""
    exs = [
        {"tenant": "t", "prompt_ids": [1, 2, 3], "output_ids": [4, 5]},
        {"tenant": "t", "prompt_ids": list(range(1, 11)),
         "output_ids": [20, 21, 22]},
        {"tenant": "t", "prompt_ids": [6], "output_ids": [7]},
    ]
    batches = pack_examples(exs, batch_size=2, seq_len=6)
    assert len(batches) == 2
    for b in batches:
        assert b["tokens"].shape == (2, 6)
        assert b["mask"].shape == (2, 6)
    np.testing.assert_array_equal(
        batches[0]["tokens"][0], [1, 2, 3, 4, 5, 0]
    )
    np.testing.assert_array_equal(
        batches[0]["mask"][0], [0, 0, 0, 1, 1, 0]
    )
    # 10-token prompt keeps its TAIL (3 slots) ahead of the 3 outputs.
    np.testing.assert_array_equal(
        batches[0]["tokens"][1], [8, 9, 10, 20, 21, 22]
    )
    np.testing.assert_array_equal(
        batches[0]["mask"][1], [0, 0, 0, 1, 1, 1]
    )
    # Ragged tail: row 1 is all padding, mask 0 everywhere.
    assert batches[1]["mask"][1].sum() == 0
    with pytest.raises(ValueError, match="batch_size"):
        pack_examples(exs, 0, 6)


# ---------------------------------------------------------------------------
# SampleStream: per-tenant take + resumable position
# ---------------------------------------------------------------------------


def test_sample_stream_position_roundtrip(tmp_path):
    """take(tenant) returns only that tenant's examples while the
    position advances over everything scanned; a new stream seeked to
    the saved state sees only records appended after it."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d)
    for i in range(6):
        tenant = "t0" if i % 2 == 0 else "t1"
        w.log(_rec(i, tenant=tenant, prompt=[1, i], output=[2, i]))
    w.close()
    s = SampleStream(d, SampleFilter(dedup_prefix=2))
    got = s.take("t0")
    assert [e["output_ids"] for e in got] == [[2, 0], [2, 2], [2, 4]]
    pos = s.state()

    # Append more records; a fresh stream from `pos` sees ONLY them.
    w = requestlog.RequestLogWriter(d)
    w.log(_rec(6, tenant="t0", prompt=[1, 6], output=[2, 6]))
    w.close()
    s2 = SampleStream(d, SampleFilter(dedup_prefix=2), state=pos)
    got2 = s2.take("t0")
    assert [e["output_ids"] for e in got2] == [[2, 6]]


# ---------------------------------------------------------------------------
# RefreshTrainer: frozen base, resume parity
# ---------------------------------------------------------------------------


def test_refresh_trains_factors_only(base, trainer):
    """The frozen-base contract: refreshed factors differ from the
    warm start, and merging them onto the UNCHANGED base is the whole
    artifact (no base leaf trained — lora_optimizer's freeze)."""
    _, _, params = base
    factors, info = trainer.refresh(_examples(6), tenant="t0")
    assert info["steps"] == 2 * 3  # epochs x ceil(6/2) batches...
    assert factors and all(
        ("lora_a" in f and "lora_b" in f) for f in factors.values()
    )
    assert any(
        np.any(np.asarray(f["lora_b"]) != 0.0)
        for f in factors.values()
    ), "training must move the zero-initialized B factors"
    assert all(np.isfinite(info["losses"]))
    # Merging onto the base is valid (shape/site agreement with the
    # serving params — what AdapterPool.register re-validates).
    merged = merge_adapter(params, factors, alpha=trainer.alpha)
    assert jax.tree.all(jax.tree.map(
        lambda x: bool(np.all(np.isfinite(np.asarray(x)))), merged
    ))


def test_refresh_resume_bitwise_parity(trainer, tmp_path):
    """Checkpoint round-trip mid-refresh: leg 1 stops after 2 steps,
    leg 2 resumes from the manager — factors bitwise-identical to the
    uninterrupted control, and the checkpointed data_state carries the
    request-log position."""
    exs = _examples(8, seed=3)
    control, cinfo = trainer.refresh(
        exs, tenant="t0", log_state={"epoch": 1, "offset": 8}
    )
    with AsyncCheckpointManager(str(tmp_path / "ck")) as m:
        f1, i1 = trainer.refresh(
            exs, tenant="t0", log_state={"epoch": 1, "offset": 8},
            manager=m, max_steps=2,
        )
        assert i1["steps"] == 2 and m.latest_step() == 2
        # The persisted data_state carries the log position + tenant.
        _, _, ds = m.restore_full(trainer.init_state())
        assert ds["log"] == {"epoch": 1, "offset": 8}
        assert ds["tenant"] == "t0"
        f2, i2 = trainer.refresh(
            exs, tenant="t0", log_state={"epoch": 1, "offset": 8},
            manager=m,
        )
    assert i2["resumed_from"] == 2
    assert i1["steps"] + i2["steps"] == cinfo["steps"]
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool(
            np.array_equal(np.asarray(a), np.asarray(b))
        ),
        control, f2,
    )), "resumed refresh must be bitwise the uninterrupted control"
    # And the loss trajectories agree step for step across the seam.
    np.testing.assert_array_equal(
        np.asarray(cinfo["losses"], np.float32),
        np.asarray(i1["losses"] + i2["losses"], np.float32),
    )


def test_refresh_preemption_sigterm_then_resume(
    trainer, tmp_path, monkeypatch
):
    """The PR 4 leg end to end: SIGTERM mid-refresh inside the grace
    window stops fit, the emergency checkpoint commits, refresh()
    returns preempted with no factors, and the SAME call made again
    resumes schedule-identical to the uninterrupted control."""
    exs = _examples(8, seed=4)
    control, _ = trainer.refresh(exs, tenant="t0")

    orig_step = trainer._step
    calls = {"n": 0}

    def stepper(state, batch, rng):
        calls["n"] += 1
        if calls["n"] == 2:
            os.kill(os.getpid(), signal.SIGTERM)
        return orig_step(state, batch, rng)

    with AsyncCheckpointManager(str(tmp_path / "ck")) as m:
        monkeypatch.setattr(trainer, "_step", stepper)
        with ft_preemption.PreemptionGuard(grace_s=60.0):
            f1, i1 = trainer.refresh(exs, tenant="t0", manager=m)
        assert f1 is None and i1["preempted"]
        assert i1["steps"] == 2 and m.latest_step() == 2
        monkeypatch.setattr(trainer, "_step", orig_step)
        f2, i2 = trainer.refresh(exs, tenant="t0", manager=m)
    assert not i2["preempted"] and i2["resumed_from"] == 2
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool(
            np.array_equal(np.asarray(a), np.asarray(b))
        ),
        control, f2,
    ))


def test_refresh_fp8_lora_cell(base):
    """The fp8 x LoRA training cell this PR opens: the refresh model's
    projections run Fp8Dense WITH adapter factors; amax rings ride
    state.precision; losses stay finite and factors move."""
    cfg, _, params = base
    tr = RefreshTrainer(
        cfg, params, rank=2, batch_size=2, seq_len=12,
        learning_rate=0.05, precision="fp8", epochs=1,
    )
    assert tr.policy.use_fp8
    state = tr.init_state()
    assert state.precision and state.precision.get("fp8"), (
        "fp8 amax rings must ride the train state"
    )
    factors, info = tr.refresh(_examples(4, seed=5), tenant="t0")
    assert all(np.isfinite(info["losses"]))
    assert any(
        np.any(np.asarray(f["lora_b"]) != 0.0)
        for f in factors.values()
    )


# ---------------------------------------------------------------------------
# FlywheelController: trigger, lease refusal + retry, telemetry
# ---------------------------------------------------------------------------


class _StubTrainer:
    """Controller-unit stand-in: returns fixed factors instantly."""

    def __init__(self, factors):
        self.alpha = 16.0
        self.factors = factors
        self.calls = []

    def refresh(self, examples, **kw):
        self.calls.append((len(examples), kw.get("tenant")))
        return self.factors, {
            "steps": 1, "preempted": False,
            "losses": [1.0, 0.5],
            "log_state": kw.get("log_state"),
            "tenant": kw.get("tenant"),
        }


class _StubSession:
    def __init__(self, pool):
        self.adapter_pool = pool


def _fill_log_and_meter(d, n, tenant="t0", start=0):
    w = requestlog.RequestLogWriter(d)
    for i in range(start, start + n):
        r = _rec(i, tenant=tenant, prompt=[1, i], output=[2, i, 3])
        w.log(r)
        metering.meter().ingest(r)
    w.close()


def _make_pool(base, adapter):
    from tpudl.serve.lora import AdapterPool

    cfg, _, _ = base
    pool = AdapterPool(cfg, r_max=2, num_slots=2, num_pages=5)
    pool.register("t0", adapter)
    return pool


def test_controller_triggers_at_min_records(base, tmp_path):
    adapter = make_adapter(base, seed=1)
    pool = _make_pool(base, adapter)
    stub = _StubTrainer(make_adapter(base, seed=2))
    ctl = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=4,
    )
    _fill_log_and_meter(str(tmp_path), 3)
    assert ctl.poll() == []  # 3 < 4: below threshold
    _fill_log_and_meter(str(tmp_path), 2, start=3)
    entries = ctl.poll()
    assert len(entries) == 1 and entries[0]["tenant"] == "t0"
    assert entries[0]["records_consumed"] == 5
    assert entries[0]["swapped"] is True
    assert stub.calls == [(5, "t0")]
    # Telemetry + persisted state.
    reg = obs_counters.registry()
    assert reg.counter("flywheel_refreshes_total").value == 1
    assert reg.counter("flywheel_records_consumed_total").value == 5
    assert os.path.isfile(ctl.state_path)
    # Re-poll with no new traffic: armed but below threshold again.
    assert ctl.poll() == []
    # The NEXT refresh consumes only post-position records.
    _fill_log_and_meter(str(tmp_path), 4, start=5)
    entries = ctl.poll()
    assert entries[0]["records_consumed"] == 4
    assert stub.calls[-1] == (4, "t0")


def test_controller_never_swaps_under_lease(base, tmp_path):
    """The safe-publish contract: register under an active lease is
    REFUSED; the controller stashes the factors and lands the swap at
    the next poll after release."""
    adapter = make_adapter(base, seed=1)
    pool = _make_pool(base, adapter)
    pool.acquire("t0")  # a seated request holds the lease
    stub = _StubTrainer(make_adapter(base, seed=2))
    ctl = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=2,
    )
    _fill_log_and_meter(str(tmp_path), 3)
    entries = ctl.poll()
    assert len(entries) == 1 and entries[0]["swapped"] is False
    assert ctl.pending_swaps == ["t0"]
    assert pool.stats()["leased"] == 1, "lease untouched by refusal"

    pool.release("t0")
    ctl.poll()  # retry lands the stashed swap
    assert ctl.pending_swaps == []
    # History entry was patched in place.
    assert ctl.history[-1]["swapped"] is True
    # The published factors are the refreshed ones.
    pool.acquire("t0")
    pool.release("t0")


def test_controller_state_persists_and_report_renders(
    base, tmp_path, capsys
):
    from tpudl.obs import report as obs_report

    adapter = make_adapter(base, seed=1)
    pool = _make_pool(base, adapter)
    stub = _StubTrainer(make_adapter(base, seed=2))
    ctl = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=2,
    )
    _fill_log_and_meter(str(tmp_path), 3)
    ctl.poll()

    # A NEW controller (process restart) reloads positions/history.
    ctl2 = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=2,
    )
    assert ctl2.history and ctl2.history[0]["records_consumed"] == 3
    assert ctl2.poll() == [], (
        "restart must not re-consume already-refreshed records"
    )

    rc = obs_report.main(["--flywheel", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "t0" in out and "flywheel refreshes: 1" in out
    with open(ctl.state_path) as f:
        blob = json.load(f)
    assert blob["history"][0]["log_position"]["offset"] == 3


# ---------------------------------------------------------------------------
# the promotion gate: held-out eval before register, roll back on fail
# ---------------------------------------------------------------------------


class _GateStubTrainer(_StubTrainer):
    """A stub WITH ``evaluate`` — its presence arms the gate. Scores
    are scripted per side: the refreshed factors score ``new``, the
    prior adapter (or base, when None) scores ``prior``."""

    def __init__(self, factors, new=1.0, prior=2.0):
        super().__init__(factors)
        self.scores = {"new": new, "prior": prior}
        self.eval_calls = []

    def evaluate(self, examples, adapter=None):
        side = "new" if adapter is self.factors else "prior"
        self.eval_calls.append((len(examples), side))
        return self.scores[side]


def test_gate_holds_out_tail_and_promotes_on_pass(base, tmp_path):
    pool = _make_pool(base, make_adapter(base, seed=1))
    stub = _GateStubTrainer(make_adapter(base, seed=2), new=1.0, prior=2.0)
    ctl = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=4,
        holdout_frac=0.25,
    )
    _fill_log_and_meter(str(tmp_path), 8)
    entries = ctl.poll()
    assert len(entries) == 1
    gate = entries[0]["gate"]
    assert gate is not None and gate["passed"] is True
    assert gate["held_out_new"] == 1.0
    assert gate["held_out_prior"] == 2.0
    assert gate["holdout_records"] == 2  # round(8 * 0.25)
    # The held-out tail never reached training.
    assert stub.calls == [(6, "t0")]
    assert {n for n, _ in stub.eval_calls} == {2}
    assert entries[0]["swapped"] is True
    reg = obs_counters.registry()
    assert reg.counter("flywheel_promotions_rejected").value == 0


def test_gate_rejects_worse_factors_and_rolls_back(base, tmp_path):
    pool = _make_pool(base, make_adapter(base, seed=1))
    stub = _GateStubTrainer(make_adapter(base, seed=2), new=3.0, prior=2.0)
    ctl = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=4,
        holdout_frac=0.25,
    )
    _fill_log_and_meter(str(tmp_path), 8)
    entries = ctl.poll()
    assert len(entries) == 1
    assert entries[0]["rejected"] is True
    assert entries[0]["swapped"] is False
    assert entries[0]["gate"]["passed"] is False
    reg = obs_counters.registry()
    assert reg.counter("flywheel_promotions_rejected").value == 1
    # Rolled back but CONSUMED: the same rejected samples must not
    # retrigger a refresh loop at the next poll.
    assert ctl.poll() == []
    assert len(stub.calls) == 1
    # Fresh traffic + a trainer that now produces good factors -> the
    # flywheel recovers on its own.
    stub.scores["new"] = 1.5
    _fill_log_and_meter(str(tmp_path), 4, start=8)
    entries = ctl.poll()
    assert entries and entries[0]["gate"]["passed"] is True
    assert entries[0]["swapped"] is True
    assert reg.counter("flywheel_promotions_rejected").value == 1


def test_gate_tolerance_and_disable(base, tmp_path):
    # Within gate_tol: slightly-worse held-out loss still promotes
    # (the knob absorbs eval noise on small holdouts).
    pool = _make_pool(base, make_adapter(base, seed=1))
    stub = _GateStubTrainer(make_adapter(base, seed=2), new=2.1, prior=2.0)
    ctl = FlywheelController(
        _StubSession(pool), str(tmp_path), stub, min_records=4,
        holdout_frac=0.25, gate_tol=0.5,
    )
    _fill_log_and_meter(str(tmp_path), 8)
    entries = ctl.poll()
    assert entries[0]["gate"]["passed"] is True and entries[0]["swapped"]
    # holdout_frac=0 disables the gate entirely: all records train.
    pool2 = _make_pool(base, make_adapter(base, seed=3))
    stub2 = _GateStubTrainer(make_adapter(base, seed=4), new=9.0, prior=1.0)
    d2 = os.path.join(str(tmp_path), "nogate")
    os.makedirs(d2)
    ctl2 = FlywheelController(
        _StubSession(pool2), d2, stub2, min_records=4, holdout_frac=0.0,
    )
    _fill_log_and_meter(d2, 8)
    entries = ctl2.poll()
    assert entries[0]["gate"] is None
    assert entries[0]["swapped"] is True
    assert stub2.calls == [(8, "t0")]
    assert stub2.eval_calls == []


# ---------------------------------------------------------------------------
# e2e acceptance: serve -> log -> filter -> refresh -> hot-swap
# ---------------------------------------------------------------------------


def test_flywheel_end_to_end(base, trainer, monkeypatch, tmp_path):
    """The acceptance loop on a live session: traffic with sample
    capture on -> durable log -> meter delta trips the controller ->
    LoRA refresh -> safe hot-swap -> the SAME prompts now serve
    measurably different tokens, with ZERO recompiles in the serving
    steady state (before and after the swap: adapter pages are data,
    not programs)."""
    _, model, params = base
    adapter = make_adapter(base, seed=1, b_scale=0.05)
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapter},
    )
    monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG_SAMPLES", "1")
    log_dir = str(tmp_path / "reqlog")
    requestlog.enable(log_dir)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, 100, size=5).tolist() for _ in range(8)
    ]
    warm_prompts = [
        rng.integers(1, 100, size=5).tolist() for _ in range(2)
    ]
    reqs = lambda tag: [  # noqa: E731
        Request(f"{tag}-{i}", p, max_new_tokens=6, tenant="t0")
        for i, p in enumerate(prompts)
    ]
    # Warmup drives prefill/decode/adapter programs (distinct prompts
    # so dedup doesn't shadow the audited traffic); then the audited
    # pre-swap window is recompile-free.
    session.serve([
        Request(f"warm-{i}", p, max_new_tokens=4, tenant="t0")
        for i, p in enumerate(warm_prompts)
    ])
    with assert_no_recompiles(label="flywheel pre-swap serving"):
        before = session.serve(reqs("pre"))
    assert all(r.ok for r in before.values())

    ctl = FlywheelController(
        session, log_dir, trainer, filter=SampleFilter(),
        min_records=8,
    )
    entries = ctl.poll()
    assert len(entries) == 1, "8 completed records must trip a refresh"
    entry = entries[0]
    assert entry["swapped"] is True, (
        "no request in flight -> the swap lands immediately"
    )
    assert entry["records_consumed"] >= 8
    assert entry["loss_first"] is not None

    # The refreshed factors are genuinely different from the
    # registered originals...
    refreshed = ctl.adapter("t0")
    assert any(
        not np.array_equal(
            np.asarray(refreshed[p]["lora_b"]),
            np.asarray(adapter[p]["lora_b"]),
        )
        for p in refreshed
    )
    # ...and the swap measurably changes what the SAME prompts serve,
    # still with zero recompiles (hot-swap = new pages, same program).
    with assert_no_recompiles(label="flywheel post-swap serving"):
        after = session.serve(reqs("post"))
    assert all(r.ok for r in after.values())
    changed = sum(
        list(after[f"post-{i}"].tokens) != list(before[f"pre-{i}"].tokens)
        for i in range(len(prompts))
    )
    assert changed > 0, (
        "a refreshed adapter must measurably change served outputs"
    )
    # And the served outputs ARE the refreshed adapter's (merged
    # reference parity on one prompt — the hot-swap published exactly
    # what the trainer returned).
    from tpudl.models.generate import generate

    merged = merge_adapter(params, refreshed, alpha=trainer.alpha)
    want = np.asarray(generate(
        model, merged, jnp.asarray([prompts[0]], jnp.int32),
        max_new_tokens=6,
    ))[0]
    np.testing.assert_array_equal(
        np.asarray(after["post-0"].tokens), want
    )
    requestlog.disable()
