"""Decode-path serving export (tpudl.export.decode).

The reference's substance is exported-artifact inference (reference
notebooks/cv/onnx_experiments.py:33-42,77-140: export -> session ->
run + parity); this is the decoder analog: serialize prefill + decode
with the KV cache as explicit I/O, deserialize, and reproduce live
generate() token for token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.export.decode import (
    decode_fn,
    export_decoder,
    generate_with_exported,
    load_decoder,
    prefill_fn,
)
from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

# Every test serializes/deserializes StableHLO; on a jax build without
# jax.export the conftest guard skips the module instead of erroring.
pytestmark = pytest.mark.needs_jax_export

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=64)
B, S, NEW = 2, 8, 12


def _setup():
    model = LlamaForCausalLM(CFG)
    ids = jnp.asarray(
        np.random.default_rng(3).integers(5, 500, size=(B, S)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    return model, params, ids


def test_functional_prefill_decode_match_live_generate():
    """The pure-function (explicit-cache) forms reproduce the flax
    mutable-state decode exactly, pre-serialization."""
    model, params, ids = _setup()
    want = generate(model, params, ids, max_new_tokens=NEW)
    pf, df = prefill_fn(model), decode_fn(model)
    logits, cache = jax.jit(pf)(params, ids, jnp.ones_like(ids))
    token = jnp.argmax(logits, -1).astype(jnp.int32)
    position = jnp.full((B,), S, jnp.int32)
    toks = [token]
    dstep = jax.jit(df)
    for _ in range(NEW - 1):
        logits, cache = dstep(params, cache, token, position)
        position = position + 1
        token = jnp.argmax(logits, -1).astype(jnp.int32)
        toks.append(token)
    np.testing.assert_array_equal(
        np.asarray(jnp.stack(toks, 1)), np.asarray(want)
    )


def test_exported_roundtrip_reproduces_generate(tmp_path):
    """Serialize -> deserialize -> generate: token-identical to the live
    model, through files on disk (the full reference loop)."""
    model, params, ids = _setup()
    prefix = str(tmp_path / "llama_tiny")
    export_decoder(model, params, B, S, path_prefix=prefix)
    prefill_call, decode_call = load_decoder(
        f"{prefix}.prefill.stablehlo", f"{prefix}.decode.stablehlo"
    )
    got = generate_with_exported(
        prefill_call, decode_call, params, ids, max_new_tokens=NEW,
        max_seq_len=CFG.max_seq_len,
    )
    want = generate(model, params, ids, max_new_tokens=NEW)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # The serving loop enforces the exporting model's KV-cache bound —
    # the deserialized callables cannot see it themselves.
    import pytest

    with pytest.raises(ValueError, match="max_seq_len"):
        generate_with_exported(
            prefill_call, decode_call, params, ids,
            max_new_tokens=CFG.max_seq_len, max_seq_len=CFG.max_seq_len,
        )


def test_exported_ragged_padded_batch(tmp_path):
    """The exported artifacts serve LEFT-padded ragged batches: the
    cache's per-slot validity travels as explicit I/O, so each padded
    row reproduces its unpadded generation token for token — the moment
    'a second input arrives' the serving path still answers correctly."""
    model, params, ids = _setup()
    pre, dec = export_decoder(model, params, B, S)
    prefill_call, decode_call = load_decoder(pre, dec)
    # Row 0: full-length prompt; row 1: 5 real tokens, left-padded by 3.
    short = ids[1:2, 3:]
    mask = jnp.concatenate(
        [
            jnp.ones((1, S), jnp.int32),
            jnp.concatenate(
                [jnp.zeros((1, 3), jnp.int32), jnp.ones((1, S - 3), jnp.int32)],
                axis=1,
            ),
        ],
        axis=0,
    )
    ragged_ids = jnp.concatenate(
        [ids[0:1], jnp.concatenate([jnp.zeros((1, 3), jnp.int32), short], 1)],
        axis=0,
    )
    got = generate_with_exported(
        prefill_call, decode_call, params, ragged_ids,
        attention_mask=mask, max_new_tokens=NEW, max_seq_len=CFG.max_seq_len,
    )
    want0 = generate(model, params, ids[0:1], max_new_tokens=NEW)
    want1 = generate(model, params, short, max_new_tokens=NEW)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want0[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want1[0]))
    import pytest

    with pytest.raises(ValueError, match="LEFT-padded"):
        generate_with_exported(
            prefill_call, decode_call, params, ragged_ids,
            attention_mask=mask[:, ::-1], max_new_tokens=2,
        )


def test_exported_eos_padding():
    model, params, ids = _setup()
    pre, dec = export_decoder(model, params, B, S)
    prefill_call, decode_call = load_decoder(pre, dec)
    # Force an eos that WILL be produced: run once, take the first
    # generated token of row 0 as the eos id.
    first = generate_with_exported(
        prefill_call, decode_call, params, ids, max_new_tokens=3
    )
    eos = int(first[0, 0])
    got = generate_with_exported(
        prefill_call, decode_call, params, ids, max_new_tokens=5, eos_id=eos
    )
    row = np.asarray(got)[0]
    assert row[0] == eos and np.all(row == eos)  # padded after first eos


def test_exported_early_exit_skips_decode_calls():
    """Regression: the exported serving loop early-exits when every row
    is done — a batch finishing at token 1 used to pay max_new_tokens-1
    dead decode dispatches; now it pays zero and eos-pads the output."""
    model, params, ids = _setup()
    # Batch-1 artifacts so "every row done at token 1" is constructible
    # (one row's first token IS the eos).
    pre, dec = export_decoder(model, params, 1, S)
    prefill_call, decode_call = load_decoder(pre, dec)

    calls = []

    def counting_decode(*args):
        calls.append(1)
        return decode_call(*args)

    first = generate_with_exported(
        prefill_call, decode_call, params, ids[0:1], max_new_tokens=1
    )
    eos_row0 = int(first[0, 0])
    calls.clear()
    got = generate_with_exported(
        prefill_call, counting_decode, params, ids[0:1],
        max_new_tokens=10, eos_id=eos_row0,
    )
    assert len(calls) == 0, (
        f"all-done batch ran {len(calls)} dead decode calls"
    )
    row = np.asarray(got)[0]
    assert row.shape == (10,) and np.all(row == eos_row0)

    # A live row must NOT trigger the early exit: pick an eos the row
    # does not emit in 6 tokens — every decode dispatch still happens.
    calls.clear()
    probe = np.asarray(
        generate_with_exported(
            prefill_call, decode_call, params, ids[0:1], max_new_tokens=6
        )
    )[0]
    never_eos = int(
        next(t for t in range(CFG.vocab_size) if t not in set(probe))
    )
    got2 = generate_with_exported(
        prefill_call, counting_decode, params, ids[0:1],
        max_new_tokens=6, eos_id=never_eos,
    )
    assert np.asarray(got2).shape == (1, 6)
    assert len(calls) == 5  # max_new_tokens - 1, no dead skipping

    # The readback is PACED: a mid-stream finish is only noticed at the
    # next eos_check_every boundary (per-token host syncs would
    # serialize the async dispatch pipeline), and the overshoot rows are
    # eos anyway, so outputs are unchanged.
    hit = 3
    eos_mid = int(probe[hit])
    hit = int(np.argmax(probe == eos_mid))  # first occurrence
    calls.clear()
    got3 = generate_with_exported(
        prefill_call, counting_decode, params, ids[0:1],
        max_new_tokens=12, eos_id=eos_mid, eos_check_every=1,
    )
    assert len(calls) == hit  # per-token checks: exit the step eos lands
    row3 = np.asarray(got3)[0]
    assert row3[hit] == eos_mid and np.all(row3[hit:] == eos_mid)
    import pytest

    with pytest.raises(ValueError, match="eos_check_every"):
        generate_with_exported(
            prefill_call, decode_call, params, ids[0:1],
            max_new_tokens=2, eos_id=0, eos_check_every=0,
        )


def test_decode_latency_harness_runs():
    """The latency harness (warmup-excluded, transfer/compute split)
    accepts the exported decode step — the reference's latency loop
    (onnx_experiments.py:90-104) applied to serving decode."""
    from tpudl.export.latency import latency_benchmark

    model, params, ids = _setup()
    pf = prefill_fn(model)
    _, cache = jax.jit(pf)(params, ids, jnp.ones_like(ids))
    token = jnp.zeros((B,), jnp.int32)
    position = jnp.full((B,), S, jnp.int32)
    out = latency_benchmark(
        decode_fn(model), (params, cache, token, position),
        warmup=1, iters=3,
    )
    assert out["compute"]["mean_ms"] > 0
    assert out["transfer"]["mean_ms"] > 0
    # Tail percentiles ride alongside the legacy keys (serving SLOs are
    # quoted at p99), and the warmup count is part of the record.
    for window in ("compute", "transfer"):
        stats = out[window]
        assert stats["p99_ms"] >= stats["p95_ms"] >= stats["p50_ms"]
        assert stats["max_ms"] >= stats["p99_ms"]
        assert stats["min_ms"] <= stats["p50_ms"]
    assert out["warmup"] == 1
