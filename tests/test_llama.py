"""Llama decoder + LoRA tests (BASELINE.json configs[4]).

The reference has no decoder anywhere (SURVEY.md §0); coverage follows the
same tiers as the BERT family: shapes, causality, learnability, and the
LoRA contract (trainable subset, frozen base, sharded dryrun on the
8-device mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpudl.models.llama import (
    LLAMA_TINY,
    LlamaForCausalLM,
    LlamaForSequenceClassification,
    build_llama,
    params_from_hf_llama,
)
from tpudl.models.lora import (
    LORA_RULES,
    compose_rules,
    lora_optimizer,
    merge_lora,
    trainable_param_count,
)
from tpudl.parallel.sharding import TP_TRANSFORMER_RULES, _path_str
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    make_classification_train_step,
)

TINY = LLAMA_TINY(num_labels=2, dtype=jnp.float32)


def _batch(rng, batch=4, seq=16, vocab=512):
    ids = rng.integers(5, vocab, size=(batch, seq)).astype(np.int32)
    lengths = rng.integers(seq // 2, seq + 1, size=(batch,))
    mask = (np.arange(seq)[None, :] < lengths[:, None]).astype(np.int32)
    ids = np.where(mask.astype(bool), ids, 0)
    return jnp.asarray(ids), jnp.asarray(mask)


def test_classifier_forward_shapes(rng_np):
    model = LlamaForSequenceClassification(TINY)
    ids, mask = _batch(rng_np)
    variables = model.init(jax.random.key(0), ids, mask)
    logits = model.apply(variables, ids, mask)
    assert logits.shape == (4, 2) and logits.dtype == jnp.float32


def test_causal_lm_is_actually_causal(rng_np):
    """Perturbing a future token must not change earlier logits."""
    model = LlamaForCausalLM(TINY)
    ids, _ = _batch(rng_np, batch=2, seq=12)
    variables = model.init(jax.random.key(0), ids)
    base = model.apply(variables, ids)
    perturbed = ids.at[:, 8].set((ids[:, 8] + 7) % 500 + 5)
    out = model.apply(variables, perturbed)
    np.testing.assert_allclose(
        np.asarray(out[:, :8]), np.asarray(base[:, :8]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(out[:, 8:]), np.asarray(base[:, 8:]))


def test_loss_decreases_classification():
    from tpudl.data.synthetic import synthetic_token_batches
    from tpudl.train import fit

    model = LlamaForSequenceClassification(
        LLAMA_TINY(num_labels=2, dtype=jnp.float32, vocab_size=256)
    )
    batches = list(
        synthetic_token_batches(16, seq_len=32, vocab_size=256, num_batches=40)
    )
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.asarray(batches[0]["input_ids"]),
        optax.adamw(1e-3),
        init_kwargs={},
    )
    step = jax.jit(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        )
    )
    rng = jax.random.key(1)
    first = None
    for batch in batches:
        state, metrics = step(state, batch, rng)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.7


def test_left_and_right_padding_equivalent(rng_np):
    """The kv-validity mask must hide pad positions from attention: a
    LEFT-padded batch produces the same real-token logits as its
    right-padded equivalent (positions already skip padding for RoPE;
    without the mask, left pads would be attended as garbage context)."""
    model = LlamaForCausalLM(TINY)
    b, seq, real = 2, 12, 7
    content = rng_np.integers(5, 500, size=(b, real)).astype(np.int32)
    pad = np.zeros((b, seq - real), np.int32)
    right_ids = jnp.asarray(np.concatenate([content, pad], axis=1))
    left_ids = jnp.asarray(np.concatenate([pad, content], axis=1))
    right_mask = jnp.asarray(
        np.concatenate([np.ones((b, real)), np.zeros((b, seq - real))], 1)
    ).astype(jnp.int32)
    left_mask = jnp.asarray(
        np.concatenate([np.zeros((b, seq - real)), np.ones((b, real))], 1)
    ).astype(jnp.int32)

    variables = model.init(jax.random.key(0), right_ids, right_mask)
    out_r = model.apply(variables, right_ids, right_mask)
    out_l = model.apply(variables, left_ids, left_mask)
    np.testing.assert_allclose(
        np.asarray(out_l[:, seq - real:]),
        np.asarray(out_r[:, :real]),
        rtol=1e-5,
        atol=1e-5,
    )


def test_hf_llama_import_logits_parity(rng_np):
    """params_from_hf_llama must reproduce HF torch logits (f32; GQA).

    Random-init torch model, no download — same defense as the BERT
    import test (transpose bugs, RoPE convention, GQA head grouping,
    RMSNorm placement). Reference analog: pretrained ingestion as the
    first act (reference notebooks/cv/onnx_experiments.py:19)."""
    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = LLAMA_TINY(dtype=jnp.float32)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        intermediate_size=cfg.intermediate_size,
        max_position_embeddings=cfg.max_seq_len,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        attention_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    model = LlamaForCausalLM(cfg)
    ids, mask = _batch(rng_np, batch=3, seq=20, vocab=cfg.vocab_size)
    template = model.init(jax.random.key(0), ids, mask)["params"]
    params = params_from_hf_llama(
        {k: v.detach().numpy() for k, v in hf_model.state_dict().items()},
        like=template,
    )

    with torch.no_grad():
        torch_logits = hf_model(
            input_ids=torch.from_numpy(np.asarray(ids, np.int64)),
            attention_mask=torch.from_numpy(np.asarray(mask, np.int64)),
        ).logits.numpy()
    jax_logits = np.asarray(model.apply({"params": params}, ids, mask))
    # Compare only at valid (non-pad) positions: HF's left-to-right
    # right-pad handling differs in position assignment for pads.
    valid = np.asarray(mask).astype(bool)
    np.testing.assert_allclose(
        jax_logits[valid], torch_logits[valid], rtol=2e-4, atol=2e-4
    )


def test_hf_llama_import_tied_embeddings_and_lora_graft(rng_np):
    """Tied-embedding checkpoints fall back to embed^T for lm_head, and a
    LoRA template keeps its adapter leaves through the graft."""
    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = LLAMA_TINY(dtype=jnp.float32, lora_rank=4)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        intermediate_size=cfg.intermediate_size,
        max_position_embeddings=cfg.max_seq_len,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        attention_bias=False,
        mlp_bias=False,
        tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    # Tied checkpoints on disk typically omit the alias; state_dict() may
    # materialize it — drop it to exercise the fallback.
    sd.pop("lm_head.weight", None)

    model = LlamaForCausalLM(cfg)
    ids, _ = _batch(rng_np, batch=2, seq=12, vocab=cfg.vocab_size)
    template = model.init(jax.random.key(0), ids)["params"]
    params = params_from_hf_llama(sd, like=template)

    np.testing.assert_array_equal(
        np.asarray(params["lm_head"]["kernel"]),
        np.asarray(params["model"]["embed_tokens"]["embedding"]).T,
    )
    # LoRA adapters survive the graft with their init values.
    att = params["model"]["layer_0"]["attention"]["q_proj"]
    t_att = template["model"]["layer_0"]["attention"]["q_proj"]
    assert "lora_a" in att
    np.testing.assert_array_equal(
        np.asarray(att["lora_a"]), np.asarray(t_att["lora_a"])
    )
    # Base kernel was grafted from HF (differs from init).
    assert not np.array_equal(
        np.asarray(att["kernel"]), np.asarray(t_att["kernel"])
    )


def test_registry_builds_llama_with_lora():
    model = build_llama("llama-tiny-lora", num_classes=2, dtype=jnp.float32)
    assert model.cfg.lora_rank == 16
    plain = build_llama("llama-tiny", num_classes=2)
    assert plain.cfg.lora_rank == 0
    big = build_llama("llama3-8b-lora", num_classes=2)
    assert big.cfg.hidden_size == 4096 and big.cfg.lora_rank == 16


def test_lora_starts_equal_to_base(rng_np):
    """Zero-init B means the adapted model's forward == base at step 0."""
    cfg_lora = LLAMA_TINY(num_labels=2, dtype=jnp.float32, lora_rank=4)
    model = LlamaForSequenceClassification(cfg_lora)
    ids, mask = _batch(rng_np)
    variables = model.init(jax.random.key(0), ids, mask)

    base_cfg = LLAMA_TINY(num_labels=2, dtype=jnp.float32)
    base_model = LlamaForSequenceClassification(base_cfg)
    # Same init seed: base kernels are drawn identically; adapters extra.
    strip = merge_lora(jax.tree.map(lambda x: x, variables["params"]))
    base_out = base_model.apply({"params": strip}, ids, mask)
    lora_out = model.apply(variables, ids, mask)
    np.testing.assert_allclose(
        np.asarray(lora_out), np.asarray(base_out), rtol=1e-5, atol=1e-5
    )


def test_lora_trains_only_adapters():
    """Frozen base: after optimizer steps, base kernels are bit-identical,
    adapters moved, loss decreased; trainable count is the LoRA subset."""
    from tpudl.data.synthetic import synthetic_token_batches

    cfg = LLAMA_TINY(
        num_labels=2, dtype=jnp.float32, vocab_size=256, lora_rank=4
    )
    model = LlamaForSequenceClassification(cfg)
    batches = list(
        synthetic_token_batches(16, seq_len=32, vocab_size=256, num_batches=30)
    )
    params = model.init(
        jax.random.key(0), jnp.asarray(batches[0]["input_ids"])
    )["params"]

    trainable, total = trainable_param_count(params, ("classifier",))
    assert 0 < trainable < total * 0.2, (trainable, total)

    tx = lora_optimizer(optax.adamw(3e-3), params, ("classifier",))
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.asarray(batches[0]["input_ids"]),
        tx,
        init_kwargs={},
    )
    step = jax.jit(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        )
    )
    before = jax.tree.map(np.asarray, state.params)
    rng = jax.random.key(1)
    first = None
    for batch in batches:
        state, metrics = step(state, batch, rng)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first, "LoRA training did not reduce loss"

    moved = frozen_same = 0
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(before),
        jax.tree.leaves(jax.tree.map(np.asarray, state.params)),
    ):
        p = _path_str(path)
        if p.endswith(("lora_a", "lora_b")) or "classifier" in p:
            if not np.array_equal(a, b):
                moved += 1
        else:
            assert np.array_equal(a, b), f"frozen base param {p} changed"
            frozen_same += 1
    assert moved > 0 and frozen_same > 0


def test_lora_tp_fsdp_dryrun_on_mesh(mesh8):
    """configs[4] shape at toy scale: LoRA llama on the 8-device mesh under
    TP+FSDP+LORA rules; adapters must land sharded; one step runs."""
    cfg = LLAMA_TINY(
        num_labels=2, dtype=jnp.float32, vocab_size=256, lora_rank=4
    )
    model = LlamaForSequenceClassification(cfg)
    params_init_ids = jnp.zeros((1, 16), jnp.int32)
    params = model.init(jax.random.key(0), params_init_ids)["params"]
    tx = lora_optimizer(optax.adamw(1e-3), params, ("classifier",))
    state = create_train_state(
        jax.random.key(0), model, params_init_ids, tx, init_kwargs={}
    )
    rules = compose_rules(LORA_RULES, TP_TRANSFORMER_RULES)
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh8,
        state,
        rules,
    )
    specs = {
        _path_str(p): str(s.spec)
        for p, s in jax.tree_util.tree_leaves_with_path(
            step.state_shardings.params
        )
    }
    lora_b_specs = [s for p, s in specs.items() if p.endswith("lora_b")]
    assert lora_b_specs and any("tp" in s for s in lora_b_specs), specs

    batch = {
        "input_ids": jnp.ones((16, 16), jnp.int32),
        "attention_mask": jnp.ones((16, 16), jnp.int32),
        "label": jnp.zeros((16,), jnp.int32),
    }
    state, metrics = step(state, batch, jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))
