"""Module-level payloads for the fault-tolerance spawn tests (picklable
by reference from TpuDistributor worker subprocesses)."""


def _ft_state():
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.models.resnet import ResNetTiny
    from tpudl.train import create_train_state

    model = ResNetTiny(num_classes=4)
    return create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.05, momentum=0.9),
    )


def _ft_batches(n):
    """Seeded per-host batch stream: every process regenerates the same
    local shards, so the global schedule is reproducible across
    restarts and across the control run."""
    from tpudl.data.synthetic import synthetic_classification_batches

    return list(
        synthetic_classification_batches(
            16, image_shape=(16, 16, 3), num_classes=4, num_batches=n,
            seed=7,
        )
    )


def elastic_train(ckpt_dir, total_steps=8, ckpt_every=2):
    """The resume-idempotent supervised payload: resume from the newest
    committed checkpoint (full resume state: step, rng, data position),
    train the remaining schedule with async checkpointing, and obey any
    env-configured chaos kill (TPUDL_CHAOS_* — set by the test,
    inherited through the distributor's worker env).

    Returns ``(rank, start_step, losses, final_step)`` where ``losses``
    are the per-step losses THIS attempt computed (global schedule
    steps ``start_step .. final_step``).

    Each rank trains an identical independent replica over its LOCAL
    devices (this container's CPU jaxlib cannot compile cross-process
    computations; the launch/kill/restart/resume machinery under test
    is the same either way), so every rank's loss schedule is
    bit-identical by seeding. Rank 0 is the checkpoint writer; every
    rank restores from the shared directory."""
    import jax

    from tpudl.ft import chaos
    from tpudl.ft.data import ResumableIterator
    from tpudl.ft.manager import AsyncCheckpointManager
    from tpudl.ft.supervisor import resume_run
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import compile_step, fit, make_classification_train_step

    state = _ft_state()
    mesh = make_mesh(MeshSpec(dp=-1), devices=jax.local_devices())
    step = compile_step(
        make_classification_train_step(), mesh, state, None,
        donate_state=False,
    )

    local = _ft_batches(total_steps)

    def epoch_iter(epoch):
        return iter(local)

    batches = ResumableIterator(epoch_iter)
    with AsyncCheckpointManager(ckpt_dir) as mgr:
        # mesh placement matters in multi-process: restored leaves must
        # come back as GLOBAL (replicated) arrays, not single-device.
        state, rng, batches, start = resume_run(
            mgr, state, batches, mesh=mesh
        )
        if rng is None:
            rng = jax.random.key(1)

        kill_hook = chaos.step_kill_hook()
        losses = []

        def logger(i, metrics):
            losses.append(metrics["loss"])
            if kill_hook is not None:
                # Drain the writer before dying so WHICH checkpoint is
                # committed at kill time is deterministic (torn-write
                # crash shapes are covered by the store unit tests).
                mgr.wait_until_finished()
                kill_hook(start + i)  # i is 1-based within this fit

        state, _, _ = fit(
            step, state, batches, rng,
            num_steps=total_steps - start,
            log_every=1, logger=logger,
            checkpoint_manager=mgr, checkpoint_every=ckpt_every,
        )
    return jax.process_index(), start, losses, int(state.step)


def rank_dependent_worker():
    """Rank 1 raises, rank 0 logs a clue and succeeds — drives the
    failure-report path that must include SURVIVING workers' log
    tails."""
    import jax

    if jax.process_index() == 1:
        raise RuntimeError("rank1 poisoned the well")
    print("rank0 survivor breadcrumb: saw nothing wrong")
    import sys

    sys.stdout.flush()
    return "ok-rank0"
