"""Page-granular KV migration + the serving chaos harness (ISSUE 13).

The correctness bar is byte-exactness: a request migrated mid-stream
must produce EXACTLY the tokens an uninterrupted run produces — f32
against solo ``generate()``, int8 against an uninterrupted engine run
(the pools' stored bytes ship verbatim) — with ZERO prefill dispatches
on the target. On top of that, the chaos contract: a killed replica
falls back to capped resubmission, a corrupt payload sheds as
``failed`` (never resumes), a frozen replica goes stale-unready and
recovers, and drains return in a fraction of the longest in-flight
generation with nothing dropped.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpudl.obs as obs
from tpudl.models.generate import generate, paged_decode_fn, prefill_fn
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.obs import counters as obs_counters
from tpudl.obs import exporter as obs_exporter
from tpudl.obs import spans as obs_spans
from tpudl.serve import (
    MigrationCompatError,
    MigrationCorruptError,
    Replica,
    Request,
    Router,
    ServeSession,
    chaos,
)
from tpudl.serve.cache import PagedKVCache, parse_migration

pytestmark = pytest.mark.chaos

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8
PAGE = 8


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter._reset_health_for_tests()
    yield
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter._reset_health_for_tests()


@pytest.fixture(scope="module")
def programs():
    """Shared compiled programs (one jit wrapper = one compile for the
    whole module) plus a warm migration round trip, so every timed or
    failover-sensitive test below runs compiled code — a cold XLA
    compile inside a migration window reads as a dead replica."""
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    pf = jax.jit(prefill_fn(model))
    dec = jax.jit(paged_decode_fn(model, PAGE, False))
    ids = jax.ShapeDtypeStruct((2, PROMPT_LEN), jnp.int32)
    _, template = jax.eval_shape(prefill_fn(model), params, ids, ids)
    out = {
        "model": model, "params": params, "prefill": pf,
        "decode": dec, "template": template,
    }
    src = _session(out)
    src.submit(Request("warm", [1, 2, 3], max_new_tokens=4))
    for _ in range(2):
        src.engine.step()
    payload = src.engine.export_request("warm")
    dst = _session(out)
    dst.engine.install_migrated(payload)
    while dst.engine.step():
        pass
    return out


def _session(programs, slow_s: float = 0.0, **kw):
    cache = PagedKVCache(programs["template"], page_size=PAGE)
    session = ServeSession(
        programs["prefill"], programs["decode"], programs["params"],
        programs["template"], PROMPT_LEN, cache=cache, **kw,
    )
    if slow_s:
        orig = session.engine.decode_call

        def slow(*args):
            time.sleep(slow_s)
            return orig(*args)

        session.engine.decode_call = slow
    return session


def _want(programs, req):
    return np.asarray(
        generate(
            programs["model"], programs["params"],
            jnp.asarray(req.input_ids, jnp.int32)[None, :],
            max_new_tokens=req.max_new_tokens,
        )
    )[0]


def _assert_parity(programs, requests, results):
    for req in requests:
        res = results[req.request_id]
        assert res.ok, (req.request_id, res.finish_reason)
        got = np.asarray(res.tokens)
        np.testing.assert_array_equal(
            got, _want(programs, req)[: got.shape[0]],
            err_msg=f"{req.request_id} diverged across migration",
        )


# ---------------------------------------------------------------------------
# engine-level migration contract
# ---------------------------------------------------------------------------


def test_migration_roundtrip_byte_exact_zero_prefill(programs):
    """Export mid-stream, install on a fresh engine: the continuation
    is token-for-token ``generate()``, the target pays ZERO prefill
    dispatches, and the source slot/pages are fully released."""
    src = _session(programs)
    dst = _session(programs)
    req = Request("r0", [3, 5, 7, 11, 2], max_new_tokens=20)
    src.submit(req)
    for _ in range(5):
        src.engine.step()
    free_before = src.engine.cache.free_pages
    payload = src.engine.export_request("r0")
    assert payload is not None and isinstance(payload, bytes)
    # Export frees the source seat (commit-or-invisible: payload first).
    assert all(s is None for s in src.engine._slots)
    assert src.engine.cache.free_pages > free_before
    rid = dst.engine.install_migrated(payload)
    assert rid == "r0"
    while dst.engine.step():
        pass
    res = dst.engine.results["r0"]
    assert res.finish_reason == "length"
    np.testing.assert_array_equal(
        np.asarray(res.tokens), _want(programs, req)
    )
    assert dst.engine.num_prefills == 0, (
        "migration must not re-pay prefill on the target"
    )


def test_migration_int8_pages_ship_as_int8(programs):
    """Quantized pools migrate as stored bytes: the payload's page
    arrays are int8 (+ f32 scale rows), and the migrated continuation
    is byte-exact against an UNINTERRUPTED int8 engine run (the
    quantized contract is engine-vs-engine, not engine-vs-f32)."""
    model, params = programs["model"], programs["params"]
    dec8 = jax.jit(paged_decode_fn(model, PAGE, True))

    def mk8():
        cache = PagedKVCache(
            programs["template"], page_size=PAGE, kv_dtype="int8"
        )
        return ServeSession(
            programs["prefill"], dec8, params,
            programs["template"], PROMPT_LEN, cache=cache,
        )

    req = Request("r0", [3, 5, 7, 11, 2], max_new_tokens=16)
    control = mk8()
    control.submit(req)
    want = control.collect()["r0"]
    src, dst = mk8(), mk8()
    src.submit(req)
    for _ in range(4):
        src.engine.step()
    payload = src.engine.export_request("r0")
    meta = parse_migration(payload)
    assert meta["quantized"] is True
    kinds = {
        path.rsplit("'", 2)[-2]: arr.dtype
        for path, arr in meta["_arrays"].items()
    }
    assert kinds["pages_k"] == np.int8 and kinds["pages_v"] == np.int8
    assert kinds["scale_k"] == np.float32
    dst.engine.install_migrated(payload)
    while dst.engine.step():
        pass
    assert dst.engine.results["r0"].tokens == want.tokens
    assert dst.engine.num_prefills == 0


def test_migration_crc_guard(programs):
    """Any bit flip or truncation in transfer raises
    MigrationCorruptError at the door; through the migrate inbox the
    same payload becomes a ``failed`` Result — never a resumed
    stream."""
    src = _session(programs)
    req = Request("r0", [3, 5, 7], max_new_tokens=12)
    src.submit(req)
    for _ in range(3):
        src.engine.step()
    payload = src.engine.export_request("r0")
    flipped = chaos.corrupt_payload(payload)
    assert len(flipped) == len(payload)
    assert sum(
        bin(a ^ b).count("1") for a, b in zip(payload, flipped)
    ) == 1, "corrupt_payload must flip exactly one bit"
    dst = _session(programs)
    with pytest.raises(MigrationCorruptError):
        dst.engine.install_migrated(flipped)
    with pytest.raises(MigrationCorruptError):
        parse_migration(payload[: len(payload) // 2])
    # Through the inbox (the router's hand-off path): failed Result.
    from tpudl.serve.engine import _Migrated

    dst2 = _session(programs)
    dst2.engine.migrate_inbox.append(_Migrated("r0", flipped))
    dst2.engine.step()
    res = dst2.engine.results["r0"]
    assert res.finish_reason.startswith("failed")
    assert res.tokens == []
    assert all(s is None for s in dst2.engine._slots), (
        "a corrupt payload must never seat"
    )


def test_failed_migration_bills_payload_tenant(programs):
    """The terminal record of a migration that cannot resume carries
    the payload's tenant, prompt length, and CUMULATIVE hop count —
    failed migrated requests must not be metered under ``_base`` (the
    failure class multi-tenant billing most needs to see)."""
    from tpudl.obs import metering

    # The hop count rides the payload (export stamps hops survived).
    src = _session(programs)
    src.submit(Request("rm", [3, 5, 7], max_new_tokens=12))
    src.engine.step()
    assert parse_migration(src.engine.export_request("rm"))[
        "migrations"
    ] == 0

    dst = _session(programs)
    meter = metering.meter()
    meter.reset()
    try:
        dst.engine._fail_migrated(
            "rx", RuntimeError("boom"),
            meta={
                "request": {
                    "tenant": "acme", "input_ids": [1, 2, 3, 4],
                },
                "migrations": 2,
            },
        )
        snap = meter.tenants()
        assert metering.BASE_TENANT not in snap
        a = snap["acme"]
        assert a["requests_total"] == 1
        assert a["tokens_in"] == 4
        assert a["migrations"] == 3  # 2 survived hops + this failure
        assert a["sheds"] == {"failed": 1}
        # A corrupt transfer has no parsed meta: the fallback still
        # lands the record (under _base) instead of crashing.
        dst.engine._fail_migrated("ry", RuntimeError("crc"), meta=None)
        assert meter.tenants()[metering.BASE_TENANT][
            "migrations"
        ] == 1
    finally:
        meter.reset()


def test_migration_deadline_rides_payload(programs):
    """The absolute deadline stamp rides the payload: a target inside
    the budget seats and honors the remainder; a transfer that
    exhausted it sheds as shed_timeout, never resumes."""
    src = _session(programs)
    req = Request("r0", [3, 5, 7], max_new_tokens=12, deadline_s=0.4)
    src.submit(req)
    src.engine.step()
    slot = next(
        i for i, s in enumerate(src.engine._slots) if s is not None
    )
    stamp = src.engine._slots[slot].entry.deadline
    assert stamp is not None
    payload = src.engine.export_request("r0")
    assert parse_migration(payload)["deadline_at"] == stamp
    # Transfer "takes" longer than the remaining budget:
    time.sleep(0.5)
    dst = _session(programs)
    dst.engine.install_migrated(payload)
    res = dst.engine.results["r0"]
    assert res.finish_reason == "shed_timeout"
    assert all(s is None for s in dst.engine._slots)
    # Within budget: seats and completes.
    src2 = _session(programs)
    req2 = Request("r1", [3, 5, 7], max_new_tokens=12, deadline_s=60.0)
    src2.submit(req2)
    src2.engine.step()
    dst2 = _session(programs)
    dst2.engine.install_migrated(src2.engine.export_request("r1"))
    while dst2.engine.step():
        pass
    assert dst2.engine.results["r1"].ok


def test_migration_prefix_reference_first(programs):
    """Prefix-share fleets ship a target-cached prefix as token-block
    REFERENCES (pre-leased), shrinking the payload; a cold target gets
    the full page payload; a reference-only payload against a tree
    that lost the prefix is REFUSED (MigrationCompatError), not
    resumed with holes."""
    model, params = programs["model"], programs["params"]

    def mk_share():
        return ServeSession.from_model(
            model, params, prompt_len=3 * PAGE, num_slots=2,
            paged=True, page_size=PAGE, prefix_share=True,
        )

    shared = list(range(2, 2 + PAGE))  # one full page
    prompt = shared + [31, 37, 41]
    req = Request("r0", prompt, max_new_tokens=12)
    dst = mk_share()
    dst.submit(Request("warm", shared + [51, 52], max_new_tokens=3))
    dst.collect()

    def export_from_fresh_source(skip):
        src = mk_share()
        src.submit(Request("r0", prompt, max_new_tokens=12))
        for _ in range(3):
            src.engine.step()
        return src.engine.export_request("r0", skip_prefix_tokens=skip)

    skip = dst.engine.cache.prefix_match_len(prompt)
    assert skip == PAGE
    lease = dst.engine.cache.match_and_lease(prompt)
    full_payload = export_from_fresh_source(0)
    ref_payload = export_from_fresh_source(skip)
    assert len(ref_payload) < len(full_payload)
    dst.engine.install_migrated(ref_payload, lease=lease)
    while dst.engine.step():
        pass
    res = dst.engine.results["r0"]
    got = np.asarray(res.tokens)
    want = np.asarray(
        generate(
            model, params, jnp.asarray(prompt)[None, :],
            max_new_tokens=12,
        )
    )[0]
    np.testing.assert_array_equal(got, want[: got.shape[0]])
    # Cold target: tree miss -> reference-only payload refused.
    cold = mk_share()
    with pytest.raises(MigrationCompatError, match="reference"):
        cold.engine.install_migrated(export_from_fresh_source(skip))
    # ... while the full payload seats fine and seeds the cold tree.
    cold.engine.install_migrated(export_from_fresh_source(0))
    while cold.engine.step():
        pass
    assert cold.engine.results["r0"].ok
    assert cold.engine.cache.prefix_match_len(prompt) >= PAGE, (
        "a migrated-in prompt's full pages should enter the radix tree"
    )


# ---------------------------------------------------------------------------
# router-level: failover, crash fallback, cap, drain
# ---------------------------------------------------------------------------


def test_failover_migrates_zero_reprefill_span_audited(programs, tmp_path):
    """The acceptance scenario: kill (preempt) one replica of three
    mid-decode under load — every in-flight request completes on
    survivors with byte-exact generate() parity, migrated requests
    issue ZERO prefill dispatches on the target (span-audited: one
    prefill event per request fleet-wide), and the failover token-gap
    histogram observes the stall."""
    obs.enable(str(tmp_path / "obs"))
    sessions = [_session(programs, slow_s=0.02) for _ in range(3)]
    replicas = [Replica(f"r{i}", s) for i, s in enumerate(sessions)]
    # Chaos preemption notice on r1's engine: mid-decode it turns lame
    # duck (unready, thread answering) — the migration path.
    sessions[1].engine.chaos_hooks.append(chaos.step_preempter(6))
    rng = np.random.default_rng(3)
    requests = [
        Request(
            f"q{i}",
            rng.integers(1, CFG.vocab_size, size=5).tolist(),
            max_new_tokens=int(rng.integers(14, 20)),
        )
        for i in range(6)
    ]
    with Router(replicas, scrape_interval_s=0.0) as router:
        for req in requests:
            router.submit(req)
        assert any(
            owner == "r1" for owner, _ in router._assigned.values()
        ), "nothing landed on the doomed replica — scenario is vacuous"
        results = router.collect(timeout_s=300.0)
    assert replicas[1].lame, "the chaos preemption never fired"
    assert router.num_migrations >= 1
    assert set(results) == {r.request_id for r in requests}
    _assert_parity(programs, requests, results)
    # Fleet-wide prefill accounting: exactly one per request — a
    # resubmission would re-pay one.
    assert sum(s.engine.num_prefills for s in sessions) == len(requests)
    records = obs_spans.active_recorder().records
    migrated = {
        r["request_id"]
        for r in records
        if r.get("name") == "request_migrated"
    }
    assert migrated, "no request_migrated event recorded"
    for rid in migrated:
        prefills = [
            r for r in records
            if r.get("name") == "prefill" and r.get("request_id") == rid
        ]
        assert len(prefills) == 1, (
            f"{rid}: expected exactly its original prefill span, got "
            f"{len(prefills)} — the target re-prefilled"
        )
        installs = [
            r for r in records
            if r.get("name") == "migration_install"
            and r.get("request_id") == rid
        ]
        assert len(installs) == 1
    snap = obs_counters.registry().snapshot()
    assert snap["histograms"]["serve_failover_token_gap_ms"]["count"] >= 1
    assert snap["counters"]["serve_migrations_total"] >= 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_crashed_replica_falls_back_to_resubmit(programs):
    """A chaos KILL (thread dies) leaves no payloads: the router falls
    back to today's resubmission path — parity via re-generation, and
    the fleet pays the prefill again (that is the fallback's cost)."""
    sessions = [_session(programs, slow_s=0.02) for _ in range(2)]
    replicas = [Replica(f"r{i}", s) for i, s in enumerate(sessions)]
    sessions[0].engine.chaos_hooks.append(chaos.step_killer(4))
    requests = [
        Request(f"q{i}", [3 + i, 5, 7], max_new_tokens=14)
        for i in range(4)
    ]
    with Router(
        replicas, scrape_interval_s=0.0, migrate_timeout_s=0.3
    ) as router:
        for req in requests:
            router.submit(req)
        results = router.collect(timeout_s=300.0)
    assert router.num_failovers >= 1
    assert router.num_migrations == 0
    assert replicas[0]._published["healthy"] is False
    _assert_parity(programs, requests, results)


def test_failover_resubmissions_capped(programs):
    """The ping-pong guard: with the cap at 0, the first from-scratch
    resubmission sheds the request as ``failover_exhausted`` instead
    of restarting it — a request bouncing across successively dying
    replicas terminates."""
    sessions = [_session(programs, slow_s=0.05) for _ in range(2)]
    replicas = [Replica(f"r{i}", s) for i, s in enumerate(sessions)]
    requests = [
        Request(f"q{i}", [3 + i, 5, 7], max_new_tokens=30)
        for i in range(4)
    ]
    with Router(
        replicas, scrape_interval_s=0.0, migrate=False, max_failovers=0
    ) as router:
        for req in requests:
            router.submit(req)
        doomed = {
            rid for rid, (owner, _) in router._assigned.items()
            if owner == "r0"
        }
        assert doomed
        time.sleep(0.1)
        replicas[0].lame = True  # unready; migrate=False -> resubmit
        results = router.collect(timeout_s=300.0)
    for rid in doomed:
        assert results[rid].finish_reason == "failover_exhausted", (
            rid, results[rid].finish_reason
        )
        assert results[rid].tokens == []
    survivors = set(results) - doomed
    assert all(results[rid].ok for rid in survivors)
    snap = obs_counters.registry().snapshot()
    assert snap["counters"]["serve_requests_failover_exhausted"] == len(
        doomed
    )


def test_drain_is_instant_and_drops_nothing(programs):
    """The acceptance drain bar: removing a loaded replica returns in
    < 10% of the time its longest in-flight generation still needed,
    every Result is delivered with parity, and zero requests restart
    (migrations, not failovers)."""
    step_s = 0.05
    max_new = 40
    sessions = [_session(programs, slow_s=step_s) for _ in range(2)]
    replicas = [Replica(f"d{i}", s) for i, s in enumerate(sessions)]
    requests = [
        Request(f"w{i}", [3, 5, 7 + i], max_new_tokens=max_new)
        for i in range(4)
    ]
    with Router(replicas, scrape_interval_s=0.0) as router:
        for req in requests:
            router.submit(req)
        time.sleep(8 * step_s)  # everyone mid-stream, far from done
        t0 = time.perf_counter()
        router.remove_replica("d0", drain=True, timeout_s=60.0)
        drain_s = time.perf_counter() - t0
        results = router.collect(timeout_s=300.0)
    longest_remaining_s = max_new * step_s  # conservative lower bound
    assert drain_s < 0.1 * longest_remaining_s, (
        f"drain took {drain_s:.3f}s — not < 10% of the "
        f"{longest_remaining_s:.1f}s the longest generation needed"
    )
    assert router.num_failovers == 0
    assert set(results) == {r.request_id for r in requests}
    _assert_parity(programs, requests, results)
    snap = obs_counters.registry().snapshot()
    assert snap["histograms"]["serve_drain_ms"]["count"] >= 1


def test_frozen_replica_goes_stale_then_recovers(programs):
    """A freeze mid-step: the stale-heartbeat bound flips the replica
    unready (work fails over; the frozen thread cannot answer the
    migration pull, so resubmission covers it), and when the freeze
    ends the replica publishes again and scrapes ready."""
    sessions = [_session(programs, slow_s=0.01) for _ in range(2)]
    replicas = [
        Replica("r0", sessions[0], stale_after_s=0.15),
        Replica("r1", sessions[1]),
    ]
    sessions[0].engine.chaos_hooks.append(chaos.step_freezer(3, 0.6))
    requests = [
        Request(f"q{i}", [3 + i, 5, 7], max_new_tokens=16)
        for i in range(4)
    ]
    with Router(
        replicas, scrape_interval_s=0.0, migrate_timeout_s=0.1
    ) as router:
        for req in requests:
            router.submit(req)
        results = router.collect(timeout_s=300.0)
        assert not router._ready["r0"], (
            "the freeze never flipped r0 unready via staleness"
        )
        _assert_parity(programs, requests, results)
        # The freeze ends; the loop publishes again and r0 rejoins.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not router._ready["r0"]:
            router.poll()
            time.sleep(0.02)
        assert router._ready["r0"], "r0 never recovered after the freeze"


# ---------------------------------------------------------------------------
# chaos injector units
# ---------------------------------------------------------------------------


def test_once_marker_claims_exactly_once(tmp_path):
    assert chaos.claim_once(str(tmp_path), "kill")
    assert not chaos.claim_once(str(tmp_path), "kill")
    assert chaos.claim_once(str(tmp_path), "freeze")
    assert chaos.claim_once(None, "kill")  # no dir: always claims


def test_step_killer_fires_once_at_step(tmp_path):
    hook = chaos.step_killer(5, once_dir=str(tmp_path))
    for step in range(5):
        hook(step)  # below the threshold: nothing
    with pytest.raises(chaos.ChaosKill):
        hook(5)
    hook(6)  # latched: never re-fires
    # A second engine's hook sharing the once-dir never fires at all.
    other = chaos.step_killer(5, once_dir=str(tmp_path))
    other(7)


def test_step_freezer_sleeps_injected(tmp_path):
    slept = []
    hook = chaos.step_freezer(2, 1.5, sleep=slept.append)
    hook(1)
    assert slept == []
    hook(2)
    assert slept == [1.5]
    hook(3)
    assert slept == [1.5]


def test_env_hooks_and_scrape_chaos(monkeypatch, tmp_path):
    monkeypatch.setenv("TPUDL_SERVE_CHAOS_KILL_STEP", "3")
    monkeypatch.setenv("TPUDL_SERVE_CHAOS_FREEZE_STEP", "4")
    monkeypatch.setenv("TPUDL_SERVE_CHAOS_ONCE_DIR", str(tmp_path))
    hooks = chaos.engine_step_hooks()
    assert len(hooks) == 2
    monkeypatch.delenv("TPUDL_SERVE_CHAOS_KILL_STEP")
    monkeypatch.delenv("TPUDL_SERVE_CHAOS_FREEZE_STEP")
    assert chaos.engine_step_hooks() == []

    class FakeMonitor:
        scrape_fault = None

    mon = FakeMonitor()
    assert not chaos.install_scrape_chaos(mon)
    monkeypatch.setenv("TPUDL_SERVE_CHAOS_SCRAPE_FAIL_N", "2")
    assert chaos.install_scrape_chaos(mon)
    with pytest.raises(chaos.ChaosScrapeBlackhole):
        mon.scrape_fault("m0")
    with pytest.raises(chaos.ChaosScrapeBlackhole):
        mon.scrape_fault("m0")
    mon.scrape_fault("m0")  # budget spent: clean


def test_maybe_corrupt_migration_env_gated(monkeypatch):
    payload = b"tpudl-payload-bytes"
    assert chaos.maybe_corrupt_migration(payload) == payload
    monkeypatch.setenv("TPUDL_SERVE_CHAOS_FLIP_MIGRATION", "1")
    flipped = chaos.maybe_corrupt_migration(payload)
    assert flipped != payload and len(flipped) == len(payload)


def test_corrupted_transfer_sheds_failed_never_resumes(
    programs, monkeypatch
):
    """End-to-end chaos corruption: with the env flip on, a failover
    migration's payload is corrupted in transfer — the target's crc
    sheds the request as ``failed``; it is never resumed."""
    monkeypatch.setenv("TPUDL_SERVE_CHAOS_FLIP_MIGRATION", "1")
    sessions = [_session(programs, slow_s=0.02) for _ in range(2)]
    replicas = [Replica(f"r{i}", s) for i, s in enumerate(sessions)]
    requests = [
        Request(f"q{i}", [3 + i, 5, 7], max_new_tokens=16)
        for i in range(4)
    ]
    with Router(replicas, scrape_interval_s=0.0) as router:
        for req in requests:
            router.submit(req)
        doomed = {
            rid for rid, (owner, _) in router._assigned.items()
            if owner == "r0"
        }
        assert doomed
        time.sleep(0.1)
        replicas[0].lame = True
        results = router.collect(timeout_s=300.0)
    assert router.num_migrations >= 1
    migrated_failed = [
        rid for rid in doomed
        if results[rid].finish_reason.startswith("failed")
    ]
    assert migrated_failed, (
        "corrupted migration payloads must shed as failed, got "
        f"{ {rid: results[rid].finish_reason for rid in doomed} }"
    )
    for rid in migrated_failed:
        assert results[rid].tokens == []
    snap = obs_counters.registry().snapshot()
    assert snap["counters"]["serve_migrations_failed"] >= 1
    assert "TPUDL_SERVE_CHAOS_FLIP_MIGRATION" in os.environ  # guard on


# ---------------------------------------------------------------------------
# review-round regressions
# ---------------------------------------------------------------------------


def test_pad_aligned_payload_ignores_prepinned_lease(programs):
    """A pad-aligned (non-prefix-share) source exports rows that do NOT
    follow the radix tree's canonical token->position mapping: a
    pre-pinned lease handed to import must be DROPPED (pages imported
    fully private), not spliced in over wrong KV — the continuation
    stays byte-exact and the pin is released."""
    model, params = programs["model"], programs["params"]
    share = ServeSession.from_model(
        model, params, prompt_len=2 * PAGE, num_slots=2,
        paged=True, page_size=PAGE, prefix_share=True,
    )
    prompt = list(range(2, 2 + PAGE)) + [31, 37]
    # Warm the share target's tree with the same leading page.
    share.submit(Request("warm", prompt[:PAGE] + [51], max_new_tokens=3))
    share.collect()
    # Pad-aligned source: plain paged session (seat() path, start > 0).
    src = ServeSession.from_model(
        model, params, prompt_len=2 * PAGE, num_slots=2,
        paged=True, page_size=PAGE,
    )
    req = Request("r0", prompt, max_new_tokens=10)
    src.submit(req)
    for _ in range(3):
        src.engine.step()
    assert int(src.engine.cache.start[0]) > 0  # genuinely pad-aligned
    payload = src.engine.export_request("r0")
    assert parse_migration(payload)["left_aligned"] is False
    evictable_before = share.engine.cache.radix.evictable_pages
    lease = share.engine.cache.match_and_lease(prompt)
    share.engine.install_migrated(payload, lease=lease)
    assert share.engine.cache.radix.evictable_pages == evictable_before, (
        "the dropped lease must be released (refcount back to 0)"
    )
    while share.engine.step():
        pass
    res = share.engine.results["r0"]
    got = np.asarray(res.tokens)
    want = np.asarray(
        generate(
            model, params, jnp.asarray(prompt)[None, :],
            max_new_tokens=10,
        )
    )[0]
    np.testing.assert_array_equal(got, want[: got.shape[0]])


def test_export_declines_json_unstable_request_ids(programs):
    """request_id/session_key ride the payload as JSON: an id that
    does not round-trip (tuple -> list) must DECLINE export — the
    resubmit fallback preserves the original object — instead of
    resuming under a mutated (here: unhashable) identity."""
    src = _session(programs)
    req = Request(("user7", 42), [3, 5, 7], max_new_tokens=8)
    src.submit(req)
    src.engine.step()
    assert src.engine.export_request(("user7", 42)) is None
    # The request is untouched and still completes locally.
    while src.engine.step():
        pass
    assert src.engine.results[("user7", 42)].ok


def test_migrate_out_returns_reference_payload_as_request(programs):
    """A queued migrate-inbox payload that was reference-skipped is
    whole only against the tree it was probed on: a second relocation
    must hand the REQUEST back for resubmission, never forward the
    holey payload to a target that would refuse it."""
    src = _session(programs)
    req = Request("r0", [3, 5, 7, 11, 2, 9, 4, 6], max_new_tokens=8)
    src.submit(req)
    for _ in range(2):
        src.engine.step()
    full = src.engine.export_request("r0")
    meta = parse_migration(full)
    meta["skip_tokens"] = PAGE  # simulate a reference-skipped transfer
    from tpudl.serve.cache import pack_migration
    from tpudl.serve.engine import _Migrated

    holey = pack_migration(
        {k: v for k, v in meta.items() if k not in ("_arrays", "arrays")},
        [],
    )
    holder = _session(programs)
    replica = Replica("hold", holder)
    replica.session.engine.migrate_inbox.append(_Migrated("r0", holey))
    replica.session.engine.migrate_inbox.append(_Migrated("r1", full))
    box = {
        "done": __import__("threading").Event(),
        "lock": __import__("threading").Lock(),
        "claimed": False, "abandoned": False,
        "skip": {}, "payloads": {}, "requests": {},
    }
    replica._migrate_out(box)
    assert "r0" in box["requests"], "skip>0 payload must come back as a Request"
    assert box["requests"]["r0"].request_id == "r0"
    assert "r1" in box["payloads"], "skip==0 payload forwards as-is"
