"""BERT model family tests (BASELINE.json configs[1]/[3]).

The reference's NLP family is an empty placeholder (reference
notebooks/nlp/README.md); its behavioral signature elsewhere is "load a
pretrained torch model, verify numerical parity across backends"
(reference notebooks/cv/onnx_experiments.py:19,142-144). The parity test
here applies that signature to NLP: a random-init HuggingFace torch
BertForSequenceClassification (no download — zero egress) is mapped
through params_from_hf_bert and must reproduce torch logits at f32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    params_from_hf_bert,
)
from tpudl.models.registry import build_model

TINY = BertConfig(
    vocab_size=512,
    hidden_size=64,
    num_layers=2,
    num_heads=2,
    intermediate_size=128,
    max_position_embeddings=64,
    num_labels=2,
    dtype=jnp.float32,
)


def _batch(rng, batch=4, seq=16, vocab=512):
    ids = rng.integers(5, vocab, size=(batch, seq)).astype(np.int32)
    lengths = rng.integers(seq // 2, seq + 1, size=(batch,))
    mask = (np.arange(seq)[None, :] < lengths[:, None]).astype(np.int32)
    ids = np.where(mask.astype(bool), ids, 0)
    return ids, mask


def test_forward_shapes_and_dtype(rng_np):
    model = BertForSequenceClassification(TINY)
    ids, mask = _batch(rng_np)
    variables = model.init(jax.random.key(0), ids, mask)
    logits = model.apply(variables, ids, mask)
    assert logits.shape == (4, TINY.num_labels)
    assert logits.dtype == jnp.float32


def test_bf16_compute_f32_params(rng_np):
    cfg = BertConfig(
        vocab_size=512, hidden_size=64, num_layers=1, num_heads=2,
        intermediate_size=128, max_position_embeddings=64,
    )
    assert cfg.dtype == jnp.bfloat16
    model = BertForSequenceClassification(cfg)
    ids, mask = _batch(rng_np)
    variables = model.init(jax.random.key(0), ids, mask)
    # Params stay f32 (master weights); logits come back f32.
    leaves = jax.tree_util.tree_leaves(variables["params"])
    assert all(l.dtype == jnp.float32 for l in leaves)
    logits = model.apply(variables, ids, mask)
    assert logits.dtype == jnp.float32


def test_registry_builds_bert():
    model = build_model("bert-tiny", num_classes=3)
    assert isinstance(model, BertForSequenceClassification)
    assert model.cfg.num_labels == 3
    assert model.cfg.hidden_size == 128
    base = build_model("bert-base", num_classes=2)
    assert base.cfg.hidden_size == 768 and base.cfg.num_layers == 12
    large = build_model("bert-large", num_classes=2)
    assert large.cfg.hidden_size == 1024 and large.cfg.num_layers == 24


def test_hf_weight_import_logits_parity(rng_np):
    """params_from_hf_bert must reproduce HF torch logits exactly (f32).

    Random-init torch model, no download; defends against silent transpose /
    LayerNorm-placement bugs (SURVEY.md §7.4 hard part #3)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.BertConfig(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.hidden_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        intermediate_size=TINY.intermediate_size,
        max_position_embeddings=TINY.max_position_embeddings,
        num_labels=TINY.num_labels,
        hidden_act="gelu",
    )
    torch.manual_seed(0)
    hf_model = transformers.BertForSequenceClassification(hf_cfg).eval()

    model = BertForSequenceClassification(TINY)
    ids, mask = _batch(rng_np, batch=3, seq=24)
    template = model.init(jax.random.key(0), ids, mask)["params"]
    params = params_from_hf_bert(
        {k: v.detach().numpy() for k, v in hf_model.state_dict().items()},
        like=template,
    )

    with torch.no_grad():
        torch_logits = hf_model(
            input_ids=torch.from_numpy(np.asarray(ids, np.int64)),
            attention_mask=torch.from_numpy(np.asarray(mask, np.int64)),
        ).logits.numpy()
    jax_logits = np.asarray(model.apply({"params": params}, ids, mask))
    np.testing.assert_allclose(jax_logits, torch_logits, rtol=1e-4, atol=2e-5)


def test_hf_weight_import_validates_shapes(rng_np):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    hf_cfg = transformers.BertConfig(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.hidden_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        intermediate_size=TINY.intermediate_size,
        max_position_embeddings=TINY.max_position_embeddings,
        num_labels=TINY.num_labels,
    )
    hf_model = transformers.BertForSequenceClassification(hf_cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}

    wrong = BertConfig(
        vocab_size=512, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=128, max_position_embeddings=64, dtype=jnp.float32,
    )
    ids, mask = _batch(rng_np)
    template = BertForSequenceClassification(wrong).init(
        jax.random.key(0), ids, mask
    )["params"]
    with pytest.raises(ValueError, match="shape mismatch"):
        params_from_hf_bert(sd, like=template)


def test_loss_decreases_token_task():
    """Tiny BERT learns the marker-token synthetic task (SURVEY.md §4.2
    integration-smoke tier, applied to the NLP vertical)."""
    from tpudl.data.synthetic import synthetic_token_batches
    from tpudl.train import (
        create_train_state,
        fit,
        make_classification_train_step,
    )

    cfg = BertConfig(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=2,
        intermediate_size=128,
        max_position_embeddings=64,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    batches = list(
        synthetic_token_batches(16, seq_len=32, vocab_size=256, num_batches=40)
    )
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.asarray(batches[0]["input_ids"]),
        optax.adamw(1e-3),
        init_kwargs={"train": False},
    )
    step = jax.jit(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        )
    )
    first = None
    rng = jax.random.key(1)
    for batch in batches:
        state, metrics = step(state, batch, rng)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first * 0.7, f"loss did not decrease: {first} -> {last}"


def test_bert_flash_attention_impl_matches_reference(rng_np):
    """The attend() seam end-to-end: BERT with attention_impl='flash'
    (Pallas kernel, interpreter mode on CPU) must reproduce the reference
    einsum model's logits on identical params."""
    import dataclasses

    cfg_ref = BertConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=2,
        intermediate_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0, dtype=jnp.float32,
    )
    cfg_flash = dataclasses.replace(cfg_ref, attention_impl="flash")
    ids, mask = _batch(rng_np, batch=2, seq=24, vocab=256)
    variables = BertForSequenceClassification(cfg_ref).init(
        jax.random.key(0), ids, mask
    )
    ref = BertForSequenceClassification(cfg_ref).apply(variables, ids, mask)
    out = BertForSequenceClassification(cfg_flash).apply(variables, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_attention_dropout_active_in_train_mode(rng_np):
    """Dropout on attention probabilities must change train-mode outputs
    (ADVICE.md round-1: the config field was silently unused)."""
    cfg = BertConfig(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
        intermediate_size=64, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.5, dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    ids, mask = _batch(rng_np, batch=2, seq=8, vocab=128)
    variables = model.init(jax.random.key(0), ids, mask)
    eval_logits = model.apply(variables, ids, mask, train=False)
    train_logits = model.apply(
        variables, ids, mask, train=True, rngs={"dropout": jax.random.key(7)}
    )
    assert not np.allclose(np.asarray(eval_logits), np.asarray(train_logits))
