"""KV-cache decoding (tpudl.models.generate) vs full-forward recompute.

The correctness bar: greedy decode through the cache must produce exactly
the tokens you get by re-running the full forward on the growing sequence
and taking argmax of the last logits — cache reuse is numerically
invisible (f32).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=64)
B, S, NEW = 2, 8, 6


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    ids = jnp.zeros((B, S), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    return model, params


def _greedy_reference(model, params, prompt, steps):
    """Naive decode: full forward over the growing sequence each step."""
    seq = prompt
    out = []
    for _ in range(steps):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_greedy_matches_full_forward(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(1), (B, S), 0, CFG.vocab_size)
    expected = _greedy_reference(model, params, prompt, NEW)
    got = generate(model, params, prompt, max_new_tokens=NEW)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_prefill_logits_match_forward(model_and_params):
    """Decode-mode prefill must give the same last-token logits as the
    training forward (cache write path doesn't perturb computation)."""
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(2), (B, S), 0, CFG.vocab_size)
    full = model.apply({"params": params}, prompt)[:, -1, :]
    logits, _ = model.apply(
        {"params": params},
        prompt,
        jnp.ones_like(prompt),
        decode=True,
        positions=jnp.broadcast_to(jnp.arange(S)[None, :], (B, S)),
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits[:, -1, :]), np.asarray(full), atol=1e-4
    )


def test_eos_padding(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(3), (B, S), 0, CFG.vocab_size)
    toks = generate(model, params, prompt, max_new_tokens=NEW, eos_id=None)
    eos = int(toks[0, 1])  # force an eos at step 1 of row 0
    got = generate(model, params, prompt, max_new_tokens=NEW, eos_id=eos)
    row = np.asarray(got[0])
    hits = np.where(row == eos)[0]
    assert len(hits) > 0
    # Everything after the first eos is eos.
    np.testing.assert_array_equal(row[hits[0]:], eos)


def test_sampling_temperature_changes_output(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(4), (B, S), 0, CFG.vocab_size)
    a = generate(
        model, params, prompt, max_new_tokens=NEW, temperature=1.0,
        rng=jax.random.key(5),
    )
    b = generate(
        model, params, prompt, max_new_tokens=NEW, temperature=1.0,
        rng=jax.random.key(6),
    )
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_generate_validates(model_and_params):
    model, params = model_and_params
    prompt = jnp.zeros((B, S), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, max_new_tokens=CFG.max_seq_len)
    right_padded = jnp.concatenate(
        [jnp.ones((B, S - 2), jnp.int32), jnp.zeros((B, 2), jnp.int32)],
        axis=1,
    )
    with pytest.raises(ValueError, match="LEFT-padded"):
        generate(
            model, params, prompt, attention_mask=right_padded,
            max_new_tokens=2,
        )
    with pytest.raises(ValueError, match="LEFT-padded"):
        generate(
            model, params, prompt,
            attention_mask=jnp.zeros((B, S), jnp.int32),  # no real tokens
            max_new_tokens=2,
        )


def test_validate_left_padded_rejects_nonbinary_mask():
    """Regression (ADVICE round 5): a monotone mask with a non-binary
    value (e.g. 2) passed validation but corrupts position = sum(mask)
    and cache validity — the fused host check must reject it."""
    from tpudl.models.generate import validate_left_padded

    ok = jnp.asarray([[0, 0, 1, 1], [0, 1, 1, 1]], jnp.int32)
    validate_left_padded(ok)  # binary left-padded: accepted
    bad = jnp.asarray([[0, 0, 1, 2], [0, 1, 1, 1]], jnp.int32)
    with pytest.raises(ValueError, match="binary"):
        validate_left_padded(bad)
    # Float masks with fractional values are equally corrupt.
    with pytest.raises(ValueError, match="binary"):
        validate_left_padded(jnp.asarray([[0.0, 0.5, 1.0, 1.0]]))


def _left_pad(prompt, total_len, pad_id=0):
    """[B, L] -> ([B, total_len] left-padded ids, mask)."""
    b, length = prompt.shape
    pad = total_len - length
    ids = jnp.concatenate(
        [jnp.full((b, pad), pad_id, prompt.dtype), prompt], axis=1
    )
    mask = jnp.concatenate(
        [jnp.zeros((b, pad), jnp.int32), jnp.ones((b, length), jnp.int32)],
        axis=1,
    )
    return ids, mask


def test_left_padded_matches_unpadded(model_and_params):
    """Uniform left padding is numerically invisible: same tokens as the
    unpadded batch (pad slots are masked EXACTLY — zero weight — and
    RoPE positions are mask-aware, so every real dot product is
    bit-identical)."""
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(7), (B, S), 1, CFG.vocab_size)
    want = generate(model, params, prompt, max_new_tokens=NEW)
    ids, mask = _left_pad(prompt, S + 3)
    got = generate(model, params, ids, attention_mask=mask,
                   max_new_tokens=NEW)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ragged_batch_matches_per_prompt(model_and_params):
    """A ragged left-padded batch generates per row exactly what each
    prompt generates alone — the serving path's batch-of-real-requests
    contract (the analog of the reference's inference pipeline taking
    arbitrary inputs; reference notebooks/cv/onnx_experiments.py:77-140)."""
    model, params = model_and_params
    lengths = [3, S, 5]
    rows = [
        jax.random.randint(jax.random.key(10 + i), (1, n), 1, CFG.vocab_size)
        for i, n in enumerate(lengths)
    ]
    padded = [_left_pad(r, S) for r in rows]
    ids = jnp.concatenate([p[0] for p in padded], axis=0)
    mask = jnp.concatenate([p[1] for p in padded], axis=0)
    got = generate(model, params, ids, attention_mask=mask,
                   max_new_tokens=NEW)
    for i, row in enumerate(rows):
        want = generate(model, params, row, max_new_tokens=NEW)
        np.testing.assert_array_equal(
            np.asarray(got[i]), np.asarray(want[0]), err_msg=f"row {i}"
        )


def test_top_k_and_top_p_truncation(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(20), (B, S), 1, CFG.vocab_size)
    greedy = generate(model, params, prompt, max_new_tokens=NEW)
    # top_k=1 and a top_p below any single-token mass both reduce to
    # greedy regardless of temperature.
    got_k = generate(model, params, prompt, max_new_tokens=NEW,
                     temperature=1.0, top_k=1, rng=jax.random.key(21))
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(greedy))
    got_p = generate(model, params, prompt, max_new_tokens=NEW,
                     temperature=1.0, top_p=1e-9, rng=jax.random.key(22))
    np.testing.assert_array_equal(np.asarray(got_p), np.asarray(greedy))
    # top_k=5: every sampled FIRST token lies in the prompt's top-5.
    logits, _ = model.apply(
        {"params": params}, prompt, jnp.ones_like(prompt), decode=True,
        positions=jnp.broadcast_to(jnp.arange(S)[None, :], (B, S)),
        mutable=["cache"],
    )
    top5 = np.asarray(jax.lax.top_k(logits[:, -1, :], 5)[1])
    for trial in range(5):
        got = generate(model, params, prompt, max_new_tokens=1,
                       temperature=2.0, top_k=5, rng=jax.random.key(30 + trial))
        for b in range(B):
            assert int(got[b, 0]) in top5[b], (trial, b)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, max_new_tokens=1,
                 temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, max_new_tokens=1,
                 temperature=1.0, top_p=0.0)
    # Pairing truncation with greedy is an error, not a silent no-op.
    with pytest.raises(ValueError, match="temperature > 0"):
        generate(model, params, prompt, max_new_tokens=1, top_k=50)
    with pytest.raises(ValueError, match="temperature > 0"):
        generate(model, params, prompt, max_new_tokens=1, top_p=0.9)


def test_sampling_hyperparams_do_not_recompile_decode(model_and_params):
    """temperature/top_p/eos_id ride as traced scalars: varying them
    reuses the ONE compiled decode scan (a serving process must not pay
    a model-sized compile per request's sampling config)."""
    from tpudl.models.generate import _decode_chunk

    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(60), (B, S), 1, CFG.vocab_size)
    before = _decode_chunk._cache_size()
    for temp, tp in [(0.7, 0.9), (0.8, 0.95), (1.3, 0.5)]:
        generate(model, params, prompt, max_new_tokens=9, temperature=temp,
                 top_p=tp, eos_id=3, rng=jax.random.key(61))
    added = _decode_chunk._cache_size() - before
    # At most the chunk length and the remainder length compile once each.
    assert added <= 2, added


def test_generate_rejects_zero_tokens(model_and_params):
    model, params = model_and_params
    prompt = jnp.ones((B, S), jnp.int32)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, params, prompt, max_new_tokens=0)


def test_early_exit_skips_decode_chunks(model_and_params, monkeypatch):
    """Regression: a batch whose every row is done must not pay dead
    decode chunks — finishing at token 1 runs ZERO chunks, finishing
    mid-stream skips every chunk after the one that completed it."""
    import importlib

    # tpudl.models re-exports the generate FUNCTION under the submodule's
    # name, so attribute-style import resolves to the function.
    gen_mod = importlib.import_module("tpudl.models.generate")

    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(40), (B, S), 1, CFG.vocab_size)
    probe = generate(model, params, prompt, max_new_tokens=10)

    calls = []
    real = gen_mod._decode_chunk

    def counting(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(gen_mod, "_decode_chunk", counting)

    # Every row's FIRST token as its own eos is impossible batch-wide
    # (rows differ), so drive a single row: done after token 1.
    row = prompt[0:1]
    eos_first = int(probe[0, 0])
    got = generate(model, params, row, max_new_tokens=30, eos_id=eos_first,
                   eos_check_every=4)
    assert len(calls) == 0, "all-done batch still ran decode chunks"
    np.testing.assert_array_equal(np.asarray(got[0]), eos_first)

    # Mid-stream finish: eos at generated token 6 (0-indexed 5) with
    # chunk length 4 -> exactly 2 chunks run, the other 6 skipped.
    calls.clear()
    eos_mid = int(probe[0, 5])
    first_hit = int(np.argmax(np.asarray(probe[0]) == eos_mid))
    generate(model, params, row, max_new_tokens=30, eos_id=eos_mid,
             eos_check_every=4)
    expected_chunks = -(-first_hit // 4)  # ceil((hit_idx) / chunk)
    assert len(calls) == expected_chunks, (
        f"expected {expected_chunks} chunks for eos at token index "
        f"{first_hit}, ran {len(calls)} (early exit broken)"
    )
