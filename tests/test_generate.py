"""KV-cache decoding (tpudl.models.generate) vs full-forward recompute.

The correctness bar: greedy decode through the cache must produce exactly
the tokens you get by re-running the full forward on the growing sequence
and taking argmax of the last logits — cache reuse is numerically
invisible (f32).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=64)
B, S, NEW = 2, 8, 6


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    ids = jnp.zeros((B, S), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    return model, params


def _greedy_reference(model, params, prompt, steps):
    """Naive decode: full forward over the growing sequence each step."""
    seq = prompt
    out = []
    for _ in range(steps):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_greedy_matches_full_forward(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(1), (B, S), 0, CFG.vocab_size)
    expected = _greedy_reference(model, params, prompt, NEW)
    got = generate(model, params, prompt, max_new_tokens=NEW)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_prefill_logits_match_forward(model_and_params):
    """Decode-mode prefill must give the same last-token logits as the
    training forward (cache write path doesn't perturb computation)."""
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(2), (B, S), 0, CFG.vocab_size)
    full = model.apply({"params": params}, prompt)[:, -1, :]
    logits, _ = model.apply(
        {"params": params},
        prompt,
        jnp.ones_like(prompt),
        decode=True,
        positions=jnp.broadcast_to(jnp.arange(S)[None, :], (B, S)),
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits[:, -1, :]), np.asarray(full), atol=1e-4
    )


def test_eos_padding(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(3), (B, S), 0, CFG.vocab_size)
    toks = generate(model, params, prompt, max_new_tokens=NEW, eos_id=None)
    eos = int(toks[0, 1])  # force an eos at step 1 of row 0
    got = generate(model, params, prompt, max_new_tokens=NEW, eos_id=eos)
    row = np.asarray(got[0])
    hits = np.where(row == eos)[0]
    assert len(hits) > 0
    # Everything after the first eos is eos.
    np.testing.assert_array_equal(row[hits[0]:], eos)


def test_sampling_temperature_changes_output(model_and_params):
    model, params = model_and_params
    prompt = jax.random.randint(jax.random.key(4), (B, S), 0, CFG.vocab_size)
    a = generate(
        model, params, prompt, max_new_tokens=NEW, temperature=1.0,
        rng=jax.random.key(5),
    )
    b = generate(
        model, params, prompt, max_new_tokens=NEW, temperature=1.0,
        rng=jax.random.key(6),
    )
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_generate_validates(model_and_params):
    model, params = model_and_params
    prompt = jnp.zeros((B, S), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, max_new_tokens=CFG.max_seq_len)
    with pytest.raises(NotImplementedError, match="unpadded"):
        generate(
            model,
            params,
            prompt,
            attention_mask=prompt,  # zeros = padded
            max_new_tokens=2,
        )
