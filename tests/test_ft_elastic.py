"""End-to-end fault injection: chaos-killed workers mid-fit, the
supervisor restarting the cohort, and a resume that is bit-for-bit the
uninterrupted run (ISSUE 4 acceptance path).

The injected failure is a whole-cohort SIGKILL (the whole-slice
preemption shape TPU capacity actually exhibits) plus a single-rank
failure scenario for the survivor-log reporting. Workers train
identical independent replicas over their local devices — this
container's CPU jaxlib cannot compile cross-process computations (every
pre-existing spawn-compute test fails on it with "Multiprocess
computations aren't implemented on the CPU backend"), and the machinery
under test (spawn, kill detection, classified failure report, restart,
committed-checkpoint resume) is identical either way."""

import os

import numpy as np
import pytest

from tests import ft_helpers
from tpudl.ft import chaos
from tpudl.ft.supervisor import RestartPolicy, Supervisor, SupervisorGaveUp
from tpudl.runtime.distributor import TpuDistributor, WorkerFailedError


def _distributor():
    return TpuDistributor(
        num_processes=2, platform="cpu", devices_per_process=2,
        timeout_s=240.0, peer_grace_s=4.0,
    )


@pytest.mark.slow
def test_injected_kill_supervised_restart_resumes_bitwise(
    tmp_path, monkeypatch
):
    """SIGKILL the whole cohort after global step 3 (latest COMMITTED
    checkpoint: step 2). The distributor must detect the deaths
    promptly and classify them; the supervisor must restart the cohort;
    the restarted attempt must resume from step 2 with the
    checkpointed rng and data position and finish with losses EXACTLY
    equal to an uninterrupted control run."""
    total, every = 6, 2
    ckpt = str(tmp_path / "ckpt")
    chaos_dir = str(tmp_path / "chaos")
    os.makedirs(chaos_dir)

    # Control: same schedule, no chaos, separate checkpoint dir.
    control = _distributor().run(
        ft_helpers.elastic_train, str(tmp_path / "ckpt_control"), total,
        every,
    )
    (_, c_start0, c_losses0, c_final0), (_, _, c_losses1, _) = sorted(
        control
    )
    assert c_start0 == 0 and c_final0 == total
    assert c_losses0 == c_losses1  # identical seeded replicas
    assert all(np.isfinite(c_losses0))

    # Chaos on (inherited by every spawned worker): SIGKILL each rank
    # the first time ITS step 3 completes — once per rank, so the
    # supervisor-restarted cohort survives.
    monkeypatch.setenv(chaos.ENV_KILL_AT_STEP, "3")
    monkeypatch.delenv(chaos.ENV_KILL_RANK, raising=False)
    monkeypatch.setenv(chaos.ENV_ONCE_DIR, chaos_dir)

    sup = Supervisor(
        _distributor(),
        policy=RestartPolicy(
            max_restarts=2, backoff_s=0.2, max_backoff_s=1.0
        ),
    )
    results = sup.run(ft_helpers.elastic_train, ckpt, total, every)

    # Exactly one restart; the root failures are the SIGKILLed ranks,
    # classified as signal deaths (not timeouts, not exceptions).
    assert sup.restarts == 1
    assert "signal SIGKILL" in sup.failures[0]
    assert os.path.exists(os.path.join(chaos_dir, "chaos_killed_p0"))
    assert os.path.exists(os.path.join(chaos_dir, "chaos_killed_p1"))

    (_, start0, losses0, final0), (_, start1, losses1, final1) = sorted(
        results
    )
    # The successful attempt resumed from the last COMMITTED step (2,
    # not the kill step 3 — nothing for step 3 ever committed).
    assert start0 == start1 == 2
    assert final0 == final1 == total
    assert losses0 == losses1
    # The resumed schedule IS the uninterrupted one, bit for bit
    # (params, momentum, BN stats, step counter, rng key, and the data
    # position all round-tripped through the committed checkpoint).
    assert losses0 == c_losses0[start0:]
    assert losses0[-1] == c_losses0[-1]


@pytest.mark.slow
def test_retry_budget_exhausted_reports_cohort_failures(
    tmp_path, monkeypatch
):
    """A kill that re-fires on EVERY attempt (no once-marker, and early
    enough that no checkpoint ever commits) must exhaust the retry
    budget and surface the classified failures."""
    monkeypatch.setenv(chaos.ENV_KILL_AT_STEP, "1")
    monkeypatch.delenv(chaos.ENV_KILL_RANK, raising=False)
    monkeypatch.delenv(chaos.ENV_ONCE_DIR, raising=False)

    sup = Supervisor(
        _distributor(),
        policy=RestartPolicy(
            max_restarts=1, backoff_s=0.1, max_backoff_s=0.2
        ),
    )
    with pytest.raises(SupervisorGaveUp, match="retry budget"):
        sup.run(ft_helpers.elastic_train, str(tmp_path / "ckpt"), 4, 2)
    assert sup.restarts == 1
    assert all("signal SIGKILL" in f for f in sup.failures)


@pytest.mark.slow
def test_single_rank_failure_reports_survivor_log_tails():
    """One rank raises, the other completes: the raised error must
    carry the root failure CLASSIFIED as an exception and the
    SURVIVING rank's log tail (satellite: failure reporting)."""
    with pytest.raises(WorkerFailedError) as exc_info:
        _distributor().run(ft_helpers.rank_dependent_worker)
    err = exc_info.value
    assert len(err.failures) == 1
    assert err.failures[0].pid == 1
    assert err.failures[0].kind == "exception"
    assert "rank1 poisoned the well" in str(err)
    assert "surviving-worker log tails" in str(err)
    assert "rank0 survivor breadcrumb" in str(err)
    assert 0 in err.survivor_logs