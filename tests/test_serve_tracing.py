"""Per-request distributed tracing through the serve path (ISSUE 6
tentpole piece 2): request_id propagated from admission through
prefill, every decode chunk, and completion — and ``report.py
--request <id>`` stitching one request's timeline with a TTFT
decomposition that sums (within tolerance) to the measured
TTFT + generation time."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpudl.obs as obs
from tpudl.obs import counters as obs_counters
from tpudl.obs import exporter as obs_exporter
from tpudl.obs import report as obs_report
from tpudl.obs import spans as obs_spans
from tpudl.serve import Request, ServeSession

PROMPT_LEN = 8


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter._reset_health_for_tests()
    yield
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter._reset_health_for_tests()


@pytest.fixture(scope="module")
def model_and_params():
    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

    cfg = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def _recorded_run(model, params, tmp_path, n=5, **kw):
    obs.enable(str(tmp_path / "obs"))
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2, **kw
    )
    rng = np.random.default_rng(0)
    requests = [
        Request(
            f"r{i}",
            rng.integers(1, 500, size=4).tolist(),
            max_new_tokens=int(rng.integers(3, 8)),
        )
        for i in range(n)
    ]
    results = session.serve(requests)
    rec = obs_spans.active_recorder()
    records = rec.records
    path = rec.path
    obs.disable()
    return records, path, results


def test_request_trace_legs_recorded(model_and_params, tmp_path):
    model, params = model_and_params
    records, _, results = _recorded_run(model, params, tmp_path)
    # Admission events for every request, in the queue's own push.
    queued = [
        r for r in records
        if r.get("kind") == "event" and r.get("name") == "request_queued"
    ]
    assert sorted(r["request_id"] for r in queued) == [
        f"r{i}" for i in range(5)
    ]
    # Every prefill span carries its request_id; every decode chunk
    # names the requests it advanced.
    prefills = [
        r for r in records
        if r.get("kind") == "span" and r.get("cat") == "serve_prefill"
    ]
    assert sorted(p["request_id"] for p in prefills) == [
        f"r{i}" for i in range(5)
    ]
    decodes = [
        r for r in records
        if r.get("kind") == "span" and r.get("cat") == "serve_decode"
    ]
    assert decodes and all("rids" in d for d in decodes)
    assert all(len(d["rids"]) == d["busy"] for d in decodes)
    # Completion events close each trace with the measured aggregates.
    completes = {
        r["request_id"]: r for r in records
        if r.get("kind") == "event" and r.get("name") == "request_complete"
    }
    for rid, res in results.items():
        assert completes[rid]["finish_reason"] == res.finish_reason
        assert completes[rid]["num_tokens"] == len(res.tokens)
        assert completes[rid]["ttft_s"] == pytest.approx(res.ttft_s)


def test_request_timeline_decomposition_sums(model_and_params, tmp_path):
    """The acceptance criterion: queue-wait + prefill + decode
    decomposition sums (within tolerance) to the measured
    TTFT + generation time — and queue_wait + prefill equals TTFT
    exactly (both ends measured on the same clock)."""
    model, params = model_and_params
    records, _, results = _recorded_run(model, params, tmp_path)
    for rid, res in results.items():
        tl = obs_report.build_request_timeline(records, rid)
        assert tl["found"] == {
            "queued": True, "prefill": True,
            "decode_chunks": tl["found"]["decode_chunks"],
            "complete": True,
        }
        assert tl["found"]["decode_chunks"] >= len(res.tokens) - 1
        d = tl["decomposition"]
        # Exact identity: TTFT = queue wait (submit -> seat) + prefill
        # span (seat -> first token), by construction of the engine's
        # timestamps.
        assert d["queue_wait_s"] + d["prefill_s"] == pytest.approx(
            res.ttft_s, rel=1e-6
        )
        # The full decomposition covers the request's measured life up
        # to host bookkeeping between decode chunks.
        assert d["measured_total_s"] == pytest.approx(
            res.ttft_s + (res.tpot_s or 0.0) * (len(res.tokens) - 1),
            rel=1e-6,
        )
        assert d["accounted_s"] <= d["measured_total_s"] * 1.02
        assert d["coverage"] is not None and d["coverage"] > 0.5, d
        # Timeline ordering: queued -> prefill -> chunks -> complete.
        whats = [e["what"] for e in tl["timeline"]]
        assert whats[0] == "queued" and whats[1] == "prefill"
        assert whats[-1] == "complete"


def test_report_request_cli(model_and_params, tmp_path, capsys):
    model, params = model_and_params
    _, path, results = _recorded_run(model, params, tmp_path, n=3)
    assert obs_report.main([path, "--request", "r1"]) == 0
    out = capsys.readouterr().out
    for token in ("request r1", "queued", "prefill", "decode_chunk",
                  "complete", "queue_wait", "measured ttft", "coverage"):
        assert token in out, (token, out)
    # --json round-trips the same structure.
    assert obs_report.main([path, "--request", "r1", "--json"]) == 0
    tl = json.loads(capsys.readouterr().out)
    assert tl["request_id"] == "r1"
    assert tl["num_tokens"] == len(results["r1"].tokens)
    # Unknown id: a clear error, nonzero exit.
    assert obs_report.main([path, "--request", "nope"]) == 1
    assert "no trace records" in capsys.readouterr().out


def test_shed_reason_breakdown_row(model_and_params, tmp_path, capsys):
    """The cross-request aggregation: completed and shed requests land
    in the report's serve-requests breakdown by finish_reason."""
    model, params = model_and_params
    t = [0.0]
    obs.enable(str(tmp_path / "obs"))
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        clock=lambda: t[0], queue_capacity=5,
    )
    session.submit(Request("late", [1, 2, 3], max_new_tokens=3,
                           deadline_s=1.0))
    t[0] = 5.0  # deadline passes while queued
    for i in range(4):
        session.submit(Request(f"ok{i}", [1, 2, 3], max_new_tokens=3))
    session.submit(Request("over", [1, 2, 3], max_new_tokens=3))  # full
    results = session.collect()
    rec = obs_spans.active_recorder()
    records, path = rec.records, rec.path
    obs.disable()

    assert results["late"].finish_reason == "shed_timeout"
    assert results["over"].finish_reason == "shed_capacity"
    breakdown = obs_report.serve_request_breakdown(records)
    assert breakdown["length"]["count"] == 4
    assert breakdown["shed_timeout"]["count"] == 1
    assert breakdown["shed_capacity"]["count"] == 1
    assert breakdown["shed_timeout"]["mean_queue_wait_ms"] == pytest.approx(
        5000.0
    )
    assert breakdown["length"]["tokens"] == 12
    # And the rendered report carries the row.
    assert obs_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "serve requests" in out
    assert "shed_timeout" in out and "shed_capacity" in out


def test_live_metrics_during_serve_session(model_and_params, tmp_path,
                                           monkeypatch):
    """Acceptance: with the exporter up, a live serve session's
    TTFT/TPOT/queue-wait histograms are scrapeable as Prometheus text
    and /healthz reports the engine's slot/queue state ready."""
    import urllib.request

    model, params = model_and_params
    monkeypatch.setenv("TPUDL_OBS_PORT", "0")
    try:
        session = ServeSession.from_model(
            model, params, prompt_len=PROMPT_LEN, num_slots=2
        )
        ex = obs_exporter.active_exporter()
        assert ex is not None, "ServeSession must start the exporter"
        session.serve([
            Request(f"r{i}", [1, 2, 3], max_new_tokens=4) for i in range(4)
        ])
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/metrics", timeout=10.0
        ) as r:
            text = r.read().decode()
        for name in ("serve_ttft_ms", "serve_tpot_ms",
                     "serve_queue_wait_ms"):
            assert f"# TYPE {name} summary" in text
            assert f"{name}_count" in text
        assert "serve_slots_busy" in text
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/healthz", timeout=10.0
        ) as r:
            health = json.loads(r.read().decode())
        assert health["healthy"] is True
        eng = health["sources"]["serve_engine"]
        assert eng["num_slots"] == 2 and eng["queue_depth"] == 0
        assert eng["slots_busy"] == 0  # drained
    finally:
        obs_exporter.stop_exporter()


def test_shed_timeline_is_single_completion(model_and_params, tmp_path):
    model, params = model_and_params
    t = [0.0]
    obs.enable(str(tmp_path / "obs"))
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        clock=lambda: t[0],
    )
    session.submit(Request("late", [1, 2], max_new_tokens=2, deadline_s=1.0))
    t[0] = 9.0
    session.submit(Request("ok", [1, 2], max_new_tokens=2))
    session.collect()
    records = obs_spans.active_recorder().records
    obs.disable()
    tl = obs_report.build_request_timeline(records, "late")
    assert tl["finish_reason"] == "shed_timeout"
    assert tl["found"]["prefill"] is False
    assert tl["found"]["decode_chunks"] == 0
    assert [e["what"] for e in tl["timeline"]] == ["queued", "complete"]


def test_router_trace_hops_and_router_ttft_decomposition(
    model_and_params, tmp_path
):
    """A LIVE two-replica router run records the fleet-trace hops
    (router door -> replica inbox -> admission -> prefill -> decode ->
    served -> complete) and the stitched decomposition sums to the
    router-measured TTFT: inbox_wait + queue_wait + prefill ==
    router_ttft, every term a measured duration."""
    from tpudl.serve import Replica, Router

    model, params = model_and_params
    obs.enable(str(tmp_path / "obs"))
    replicas = [
        Replica(
            f"rep{i}",
            ServeSession.from_model(
                model, params, prompt_len=PROMPT_LEN, num_slots=2
            ),
        )
        for i in range(2)
    ]
    rng = np.random.default_rng(2)
    requests = [
        Request(
            f"r{i}",
            rng.integers(1, 500, size=4).tolist(),
            max_new_tokens=int(rng.integers(3, 8)),
        )
        for i in range(5)
    ]
    with Router(replicas) as router:
        results = router.serve(requests, timeout_s=300.0)
    records = obs_spans.active_recorder().records
    obs.disable()
    assert all(res.ok for res in results.values())
    for rid, res in results.items():
        tl = obs_report.build_request_timeline(records, rid)
        assert tl["warnings"] == []
        assert tl["hops"]["routed"] is True
        assert tl["hops"]["replica"] in {"rep0", "rep1"}
        whats = [e["what"] for e in tl["timeline"]]
        assert whats[0] == "routed"
        assert "replica_dequeue" in whats and "served" in whats
        assert whats[-1] == "complete"
        d = tl["decomposition"]
        assert d["inbox_wait_s"] is not None
        assert d["router_ttft_s"] == pytest.approx(
            res.ttft_s + d["inbox_wait_s"], rel=1e-6
        )
        # The fleet acceptance identity, on real measurements.
        assert (
            d["inbox_wait_s"] + d["queue_wait_s"] + d["prefill_s"]
            == pytest.approx(d["router_ttft_s"], rel=1e-6)
        )
    # The same records render as a fleet report with every request
    # fully stitched.
    fleet = obs_report.build_fleet_report(records)
    assert fleet["num_requests"] == 5
    assert fleet["partial_traces"] == {}
    assert fleet["router_ttft"]["count"] == 5
