"""Low-precision weight tier (tpudl.quant).

Four contracts, mirroring the tiers above it: (1) RULES — the default
rule sets quantize exactly the attention/MLP projections and keep
every precision-load-bearing leaf (norms/embeddings/heads) full, with
quantize->dequantize error bounded per rule class; (2) STRUCTURE —
the quantized tree has the SAME module structure as the full-precision
tree, round-trips through an Orbax checkpoint, and a weight_dtype
model serves a FULL-precision tree bit-identically to the plain
module; (3) PARITY — quantized decode matches f32 ``generate()`` under
``assert_serving_parity``'s teacher-forced logit-margin atol mode,
both live-jitted and through the StableHLO artifact pair, and composed
with the paged int8 KV cache (weights int8 + KV int8 in one session —
the acceptance-criterion cell); (4) the shared ``LatencyStats``
summary every benchmark consumes quotes the same percentiles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.quant import (
    default_quant_rules,
    dequantize_leaf,
    dequantize_tree,
    is_quantized,
    quant_dot,
    quantize_leaf,
    quantize_model,
    quantize_tree,
    weight_bytes_report,
)
from tpudl.serve import Request, ServeSession, assert_serving_parity

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8
SLOTS = 4

#: Grid tolerances (benchmarks/parity_grid.py CELL_ATOL): near-tie
#: argmax flips only; a wide-margin divergence is a cache/matmul bug.
INT8_ATOL = 0.06
KV8_ATOL = 0.10


@pytest.fixture(scope="module")
def llama_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def bert_and_params():
    from tpudl.models.bert import BertConfig, BertForSequenceClassification

    cfg = BertConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=2,
        intermediate_size=128, max_position_embeddings=64,
        num_labels=2, dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids, mask)["params"]
    return model, params, ids, mask


def _requests(n, seed=0, max_new=(4, 16)):
    rng = np.random.default_rng(seed)
    return [
        Request(
            request_id=f"q{i}",
            input_ids=rng.integers(
                1, CFG.vocab_size, size=int(rng.integers(2, PROMPT_LEN + 1))
            ).tolist(),
            max_new_tokens=int(rng.integers(*max_new)),
        )
        for i in range(n)
    ]


def _leaf_paths(params, pred):
    """Sorted "a/b/kernel" paths of leaves matching ``pred`` (quantized
    dicts walk as ONE leaf)."""
    from tpudl.parallel.sharding import _path_str

    out = []
    jax.tree_util.tree_map_with_path(
        lambda path, leaf: out.append(_path_str(path))
        if pred(leaf)
        else None,
        params,
        is_leaf=is_quantized,
    )
    return sorted(out)


# ---------------------------------------------------------------------------
# 1. Rules: which leaves quantize, and how tightly they reconstruct
# ---------------------------------------------------------------------------


def test_llama_rule_classes(llama_and_params):
    """Default Llama rules quantize exactly the seven per-block
    projections; embeddings/norms/lm_head stay full precision."""
    model, params = llama_and_params
    qtree = quantize_tree(params, default_quant_rules(model.cfg, "int8"))
    quantized = _leaf_paths(qtree, is_quantized)
    expected = sorted(
        [
            f"model/layer_{i}/attention/{name}/kernel"
            for i in range(CFG.num_layers)
            for name in ("q_proj", "k_proj", "v_proj", "o_proj")
        ]
        + [
            f"model/layer_{i}/{name}/kernel"
            for i in range(CFG.num_layers)
            for name in ("gate_proj", "up_proj", "down_proj")
        ]
    )
    assert quantized == expected
    kept = _leaf_paths(qtree, lambda l: not is_quantized(l))
    for path in kept:
        assert "_proj" not in path, f"projection left unquantized: {path}"
    assert any("embed" in p for p in kept)
    assert any("norm" in p for p in kept)
    assert any("lm_head" in p for p in kept)


def test_bert_rule_classes(bert_and_params):
    """Default BERT rules quantize the encoder attention + MLP
    projections; embeddings/pooler/classifier stay full precision."""
    model, params, _, _ = bert_and_params
    qtree = quantize_tree(params, default_quant_rules(model.cfg, "int8"))
    quantized = _leaf_paths(qtree, is_quantized)
    assert len(quantized) == model.cfg.num_layers * 6  # q/k/v/out + 2 MLP
    for path in quantized:
        assert "encoder/" in path
    kept = _leaf_paths(qtree, lambda l: not is_quantized(l))
    assert not any("pooler" in p or "classifier" in p for p in quantized)
    assert any("embed" in p for p in kept)


def test_int8_roundtrip_bound():
    """Per-output-channel int8: |dequantized - w| <= scale/2 elementwise
    (half a quantization step at the channel's own scale)."""
    w = jax.random.normal(jax.random.key(1), (96, 48)) * jnp.linspace(
        0.01, 3.0, 48
    )
    leaf = quantize_leaf(w, "int8")
    assert leaf["qvalues"].dtype == jnp.int8
    assert leaf["qscale"].shape == (48,)
    err = np.abs(np.asarray(dequantize_leaf(leaf)) - np.asarray(w))
    bound = 0.5 * np.asarray(leaf["qscale"])[None, :] + 1e-7
    assert np.all(err <= bound), float((err - bound).max())


def test_fp8_roundtrip_bound():
    """e4m3 storage: relative error bounded by the 3-mantissa-bit grid
    (<= 2^-3 of the element) plus the subnormal floor at the channel
    scale."""
    if not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("no float8_e4m3fn in this jax build")
    w = jax.random.normal(jax.random.key(2), (64, 32)) * jnp.linspace(
        0.05, 2.0, 32
    )
    leaf = quantize_leaf(w, "fp8_e4m3")
    assert leaf["qvalues"].dtype == jnp.float8_e4m3fn
    deq = np.asarray(dequantize_leaf(leaf))
    wf = np.asarray(w)
    bound = np.abs(wf) * 2.0**-3 + np.asarray(leaf["qscale"])[None, :] * 2.0**-8
    assert np.all(np.abs(deq - wf) <= bound)


def test_rules_refuse_uncovered_leaf():
    """A >=2-D leaf no rule covers is a rule-set bug, not a default."""
    params = {"mystery": {"kernel": jnp.ones((4, 4))}}
    with pytest.raises(ValueError, match="no quantization rule"):
        quantize_tree(params, ((r"other/kernel$", "int8"),))


def test_quantize_idempotent_and_dequantize_inverse(llama_and_params):
    """Already-quantized leaves pass through untouched; dequantize
    restores the original tree STRUCTURE (values to quantized
    precision)."""
    model, params = llama_and_params
    rules = default_quant_rules(model.cfg, "int8")
    once = quantize_tree(params, rules)
    twice = quantize_tree(once, rules)
    assert jax.tree_util.tree_structure(
        once, is_leaf=is_quantized
    ) == jax.tree_util.tree_structure(twice, is_leaf=is_quantized)
    chex_like = jax.tree.map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)),
        once, twice,
    )
    assert all(jax.tree.leaves(chex_like))
    deq = dequantize_tree(once)
    assert jax.tree_util.tree_structure(deq) == jax.tree_util.tree_structure(
        params
    )


def test_weight_bytes_ratio_bar(llama_and_params):
    """int8 stores >= 3.5x fewer bytes on quantized layers than f32
    (the parity-grid acceptance bar; 4x minus the scale rows)."""
    model, params = llama_and_params
    qtree = quantize_tree(params, default_quant_rules(model.cfg, "int8"))
    report = weight_bytes_report(qtree)
    assert report["num_quantized_leaves"] == CFG.num_layers * 7
    assert report["quant_ratio"] >= 3.5


# ---------------------------------------------------------------------------
# 2. Structure: the seam never changes the tree, checkpoints round-trip
# ---------------------------------------------------------------------------


def test_weight_dtype_model_full_precision_params_bitident(llama_and_params):
    """A weight_dtype model serving an UNQUANTIZED tree runs the exact
    nn.Dense math — bit-identical logits to the plain module (the
    checkpoint-interchange half of the seam contract)."""
    import dataclasses

    model, params = llama_and_params
    qmodel = model.clone(
        cfg=dataclasses.replace(model.cfg, weight_dtype="int8")
    )
    ids = jnp.arange(1, PROMPT_LEN + 1, dtype=jnp.int32)[None, :]
    ref = model.apply({"params": params}, ids)
    got = qmodel.apply({"params": params}, ids)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    # And init declares the same tree: restored checkpoints fit both.
    qinit = qmodel.init(jax.random.key(0), ids)["params"]
    assert jax.tree_util.tree_structure(
        qinit
    ) == jax.tree_util.tree_structure(params)


def test_quant_dot_fused_matches_reference():
    """The contraction-fused form differs from dequantize-then-matmul
    only by scale-multiply association."""
    x = jax.random.normal(jax.random.key(3), (5, 64))
    w = jax.random.normal(jax.random.key(4), (64, 32))
    leaf = quantize_leaf(w, "int8")
    fused = np.asarray(quant_dot(x, leaf, impl="fused"))
    ref = np.asarray(quant_dot(x, leaf, impl="reference"))
    np.testing.assert_allclose(fused, ref, atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="impl"):
        quant_dot(x, leaf, impl="pallas")


def test_checkpoint_roundtrip_quantized_tree(llama_and_params, tmp_path):
    """A quantized tree is two ordinary arrays per kernel under the
    original key — Orbax round-trips it with no custom handlers, and
    the restored tree serves bit-identical logits."""
    import dataclasses

    from tpudl.export import load_params, save_params

    model, params = llama_and_params
    qmodel, qtree = quantize_model(model, params, "int8")
    path = str(tmp_path / "quant_ckpt")
    save_params(path, qtree)
    restored = load_params(path, like=qtree)
    flat_a = jax.tree.leaves(qtree)
    flat_b = jax.tree.leaves(restored)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ids = jnp.arange(1, PROMPT_LEN + 1, dtype=jnp.int32)[None, :]
    np.testing.assert_array_equal(
        np.asarray(qmodel.apply({"params": qtree}, ids)),
        np.asarray(qmodel.apply({"params": restored}, ids)),
    )
    assert qmodel.cfg == dataclasses.replace(model.cfg, weight_dtype="int8")


def test_bert_quantized_forward_close(bert_and_params):
    """BERT int8 weights: quantized logits track f32 within the
    quantization perturbation (encoder projections only — head is full
    precision, so logits move but stay close)."""
    model, params, ids, mask = bert_and_params
    qmodel, qtree = quantize_model(model, params, "int8")
    ref = np.asarray(model.apply({"params": params}, ids, mask))
    got = np.asarray(qmodel.apply({"params": qtree}, ids, mask))
    np.testing.assert_allclose(got, ref, atol=0.05)


# ---------------------------------------------------------------------------
# 3. Serving parity: live, composed with int8 KV, and exported
# ---------------------------------------------------------------------------


def test_quantized_decode_parity_int8(llama_and_params):
    """ServeSession.from_model(weight_dtype="int8") vs the f32
    reference under the teacher-forced logit-margin atol contract."""
    model, params = llama_and_params
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=SLOTS,
        weight_dtype="int8",
    )
    assert_serving_parity(
        session, model, params, _requests(6), atol=INT8_ATOL
    )


def test_quantized_weights_compose_with_int8_kv(llama_and_params):
    """The acceptance-criterion cell: weights int8 AND paged int8 KV in
    ONE session, parity vs f32 at atol (tolerance widened — two
    bounded perturbations stack)."""
    model, params = llama_and_params
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=SLOTS,
        weight_dtype="int8", paged=True, kv_dtype="int8",
    )
    assert_serving_parity(
        session, model, params, _requests(6, seed=1), atol=KV8_ATOL
    )


@pytest.mark.needs_jax_export
def test_exported_quantized_decoder_parity(llama_and_params):
    """The quantized decoder exports through the existing StableHLO
    path (quantized leaves are plain in_tree dicts) and the
    deserialized artifact session holds the same parity contract."""
    from tpudl.export.decode import export_serving_decoder

    model, params = llama_and_params
    qmodel, qtree = quantize_model(model, params, "int8")
    pre, dec = export_serving_decoder(
        qmodel, qtree, num_slots=SLOTS, prompt_len=PROMPT_LEN
    )
    session = ServeSession.from_artifacts(pre, dec, qtree)
    assert_serving_parity(
        session, model, params, _requests(6, seed=2), atol=INT8_ATOL
    )


# ---------------------------------------------------------------------------
# 4. LatencyStats: the one percentile summary every benchmark consumes
# ---------------------------------------------------------------------------


def test_latency_stats_shared_summary():
    from tpudl.export.latency import LatencyStats

    stats = LatencyStats.from_ms(list(range(1, 101)))
    assert stats.count == 100
    assert stats.p50_ms == pytest.approx(50.5)
    assert stats.max_ms == 100.0
    assert set(stats.as_dict()) == {
        "mean_ms", "p50_ms", "p95_ms", "p99_ms", "min_ms", "max_ms"
    }
    assert set(stats.percentiles()) == {"p50_ms", "p95_ms", "p99_ms"}
    sec = LatencyStats.from_seconds([0.001, 0.002])
    assert sec.p50_ms == pytest.approx(1.5)
    with pytest.raises(ValueError):
        LatencyStats.from_ms([])
