"""tpudl.obs.exporter: the live telemetry plane (ISSUE 6 tentpole).

The contract under test: while a process runs, ``GET /metrics`` is
valid Prometheus text rendered from the registry (scrapes racing
observation threads stay consistent), ``GET /healthz`` is a
probe-compatible liveness+readiness report that flips to 503 on a
sticky background-thread error or a stale heartbeat, ``/snapshot``
carries the full registry + live goodput — and the bounded-window
Histogram keeps every scrape O(window) with memory that stops growing
(the regression the old keep-everything implementation would fail)."""

import json
import re
import threading
import urllib.error
import urllib.request

import pytest

import tpudl.obs as obs
from tpudl.obs import counters as obs_counters
from tpudl.obs import exporter as obs_exporter
from tpudl.obs import spans as obs_spans


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Exporter/health/registry state is process-global; isolate."""
    monkeypatch.delenv("TPUDL_OBS_PORT", raising=False)
    monkeypatch.delenv("TPUDL_OBS_DIR", raising=False)
    monkeypatch.delenv("TPUDL_OBS_HIST_WINDOW", raising=False)
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter.stop_exporter()
    obs_exporter._reset_health_for_tests()
    yield
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter.stop_exporter()
    obs_exporter._reset_health_for_tests()


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# Bounded rolling-window histogram (the memory-regression satellite)
# ---------------------------------------------------------------------------


def test_histogram_window_bounds_memory_and_keeps_cumulative_totals():
    h = obs_counters.Histogram(window=8)
    for i in range(100):
        h.observe(float(i))
    # Memory is bounded by the window; count/sum stay cumulative (the
    # monotone pair rate() math needs). The old implementation kept all
    # 100 raw values — this asserts the bound itself.
    assert len(h._values) == 8
    assert h.values == [92.0, 93.0, 94.0, 95.0, 96.0, 97.0, 98.0, 99.0]
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["sum"] == sum(range(100))
    # Percentiles/min/max describe the WINDOW (recent behavior): the
    # early small values were evicted.
    assert snap["min"] == 92.0 and snap["max"] == 99.0
    assert 92.0 <= snap["p50"] <= 99.0
    # Snapshot keys unchanged from the unbounded implementation.
    assert set(snap) == {
        "count", "sum", "min", "max", "mean", "p50", "p95", "p99",
    }


def test_histogram_under_window_is_exact_and_env_sets_default(monkeypatch):
    h = obs_counters.Histogram(window=16)
    for v in [1.0, 2.0, 3.0]:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 3 and snap["min"] == 1.0 and snap["p50"] == 2.0
    assert h.count == 3 and h.values == [1.0, 2.0, 3.0]

    monkeypatch.setenv("TPUDL_OBS_HIST_WINDOW", "4")
    h2 = obs_counters.Histogram()
    assert h2.window == 4
    for i in range(10):
        h2.observe(i)
    assert len(h2.values) == 4 and h2.count == 10
    with pytest.raises(ValueError, match="window"):
        obs_counters.Histogram(window=0)


def test_registry_histogram_growth_is_bounded(monkeypatch):
    """The acceptance regression test: a registry histogram fed far
    past its window holds exactly window values — a long-lived serving
    process's telemetry memory is a constant, not a leak."""
    monkeypatch.setenv("TPUDL_OBS_HIST_WINDOW", "32")
    reg = obs_counters.Registry()
    h = reg.histogram("serve_ttft_ms")
    for i in range(32 * 50):
        h.observe(float(i % 7))
    assert len(h._values) == 32
    assert h.snapshot()["count"] == 32 * 50


# ---------------------------------------------------------------------------
# /metrics: Prometheus text conformance
# ---------------------------------------------------------------------------

# One metric line: name, optional {labels}, a float/int/NaN/Inf value.
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"[^\"]*\")*\})?"
    r" (NaN|[+-]?Inf|[+-]?[0-9]*\.?[0-9]+([eE][+-]?[0-9]+)?)$"
)


def test_metrics_prometheus_text_conformance():
    reg = obs_counters.registry()
    reg.counter("bytes_ingested").inc(1234)
    reg.gauge("serve_slots_busy").set(3)
    h = reg.histogram("serve ttft.ms")  # name needs sanitizing
    for v in [10.0, 20.0, 30.0, 40.0]:
        h.observe(v)
    hb = obs_exporter.Heartbeat("train_loop")
    hb.beat(step=7)
    with obs_exporter.ObsExporter(port=0) as ex:
        status, text = _get(f"http://127.0.0.1:{ex.port}/metrics")
    assert status == 200
    lines = text.strip().splitlines()
    types = {}
    for line in lines:
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            types[name] = kind
            continue
        assert _PROM_LINE.match(line), f"malformed exposition line: {line!r}"
    assert types["bytes_ingested"] == "counter"
    assert types["serve_slots_busy"] == "gauge"
    # The sanitized histogram renders as a summary: quantile rows plus
    # the cumulative _sum/_count pair.
    assert types["serve_ttft_ms"] == "summary"
    assert 'serve_ttft_ms{quantile="0.5"} 25.0' in lines
    assert "serve_ttft_ms_sum 100.0" in lines
    assert "serve_ttft_ms_count 4" in lines
    # Heartbeat age rides as a gauge.
    assert types["train_loop_heartbeat_age_s"] == "gauge"
    assert any(l.startswith("train_loop_heartbeat_age_s ") for l in lines)


def test_metrics_scrape_races_observers():
    """Scrapes must parse and stay internally consistent while four
    threads hammer the instruments — the concurrent scrape-vs-observe
    thread-safety bar."""
    reg = obs_counters.registry()
    stop = threading.Event()

    def work():
        h = reg.histogram("lat_ms")
        c = reg.counter("events")
        while not stop.is_set():
            h.observe(1.0)
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        with obs_exporter.ObsExporter(port=0) as ex:
            url = f"http://127.0.0.1:{ex.port}/metrics"
            last_count = -1
            for _ in range(10):
                status, text = _get(url)
                assert status == 200
                count = sum_ = None
                for line in text.splitlines():
                    if line.startswith("lat_ms_count "):
                        count = int(line.split()[1])
                    elif line.startswith("lat_ms_sum "):
                        sum_ = float(line.split()[1])
                    elif not line.startswith("#"):
                        assert _PROM_LINE.match(line), line
                if count is not None:
                    # Counts only move forward across scrapes, and
                    # every 1.0-valued observation keeps sum ~= count
                    # (each taken under the instrument lock, so both
                    # are internally consistent even mid-hammer).
                    assert count >= last_count
                    last_count = count
                    assert sum_ is not None and abs(sum_ - count) <= 4
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert last_count > 0


# ---------------------------------------------------------------------------
# /healthz: sources, sticky errors, heartbeats
# ---------------------------------------------------------------------------


def test_healthz_reports_sources_and_flips_503():
    obs_exporter.register_health_source(
        "serve_engine", lambda: {"healthy": True, "slots_busy": 2}
    )
    with obs_exporter.ObsExporter(port=0) as ex:
        url = f"http://127.0.0.1:{ex.port}/healthz"
        status, body = _get(url)
        assert status == 200
        h = json.loads(body)
        assert h["healthy"] is True
        assert h["sources"]["serve_engine"]["slots_busy"] == 2

        obs_exporter.register_health_source(
            "slo", lambda: {"healthy": False, "burning": ["ttft_p99"]}
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10.0)
        assert ei.value.code == 503
        h = json.load(ei.value)
        assert h["healthy"] is False
        assert h["sources"]["slo"]["burning"] == ["ttft_p99"]

        # A RAISING source is an unhealthy source, not a broken probe.
        obs_exporter.unregister_health_source("slo")
        obs_exporter.register_health_source(
            "boom", lambda: (_ for _ in ()).throw(RuntimeError("dead"))
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10.0)
        assert ei.value.code == 503
        assert "dead" in json.load(ei.value)["sources"]["boom"]["error"]


def test_healthz_flips_on_sticky_metric_fetcher_error():
    """The failure /healthz exists for: the MetricFetcher's worker dies
    on a poisoned readback, the error is sticky, and the probe reports
    unhealthy from the moment the worker dies — including after
    close()."""
    from tpudl.train.metrics import MetricFetcher

    class _Boom:
        def __array__(self, *a, **k):
            raise RuntimeError("poisoned readback")

    fetcher = MetricFetcher(window=4)
    try:
        fetcher.submit(0, {"loss": _Boom()}, 1)
        # The worker dies asynchronously; flush surfaces the error.
        with pytest.raises(RuntimeError, match="poisoned"):
            fetcher.flush()
        with obs_exporter.ObsExporter(port=0) as ex:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ex.port}/healthz", timeout=10.0
                )
            assert ei.value.code == 503
            src = json.load(ei.value)["sources"]["metric_fetcher"]
            assert src["healthy"] is False
            assert "poisoned readback" in src["error"]
    finally:
        fetcher.close()
    # Sticky THROUGH close: the dead worker stays visible post-mortem.
    assert fetcher.health()["healthy"] is False
    assert obs_exporter.health_snapshot()["healthy"] is False


def test_healthz_flips_on_sticky_checkpoint_writer_error(tmp_path):
    """Same bar for the ft writer thread: the health view of a write
    failure survives the step path consuming the deferred exception."""
    from tpudl.ft.writer import AsyncCheckpointWriter

    class BoomStore:
        def write(self, *a, **k):
            raise OSError("disk gone")

        def retain(self):
            pass

    w = AsyncCheckpointWriter(BoomStore())
    w.submit(0, [])
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        w.wait()
    # The step path consumed the deferred error — health still reports
    # it, sticky.
    assert w.health()["healthy"] is False
    assert "disk gone" in w.health()["error"]
    snap = obs_exporter.health_snapshot()
    assert snap["sources"]["checkpoint_writer"]["healthy"] is False
    # wait() consumed the one-shot deferred error; close() is clean —
    # but the health view stays unhealthy regardless.
    w.close()
    assert w.health()["healthy"] is False


def test_heartbeat_staleness_and_stop():
    t = [0.0]
    hb = obs_exporter.Heartbeat(
        "train_loop", stale_after=10.0, clock=lambda: t[0]
    )
    hb.beat(step=5)
    t[0] = 5.0
    h = obs_exporter.health_snapshot()
    assert h["healthy"] is True
    assert h["heartbeats"]["train_loop"]["age_s"] == 5.0
    assert h["heartbeats"]["train_loop"]["step"] == 5
    # Running + stale = hung: unhealthy.
    t[0] = 30.0
    h = obs_exporter.health_snapshot()
    assert h["healthy"] is False
    assert h["heartbeats"]["train_loop"]["stale"] is True
    # Stopped (finished) is never stale, whatever the age.
    hb.stop()
    h = obs_exporter.health_snapshot()
    assert h["healthy"] is True
    assert h["heartbeats"]["train_loop"]["running"] is False


def test_heartbeat_staleness_adapts_to_beat_cadence():
    """A loop whose dispatch windows legitimately take minutes must not
    read as hung between beats: the stale threshold stretches to
    adaptive_factor x the established beat interval."""
    t = [0.0]
    hb = obs_exporter.Heartbeat(
        "train_loop", stale_after=10.0, clock=lambda: t[0],
        adaptive_factor=5.0,
    )
    hb.beat()
    t[0] = 100.0
    hb.beat()  # interval 100s >> stale_after
    assert hb.stale_threshold_s() == 500.0
    # 3 intervals late: still healthy (inside 5x the cadence)...
    t[0] = 400.0
    assert hb.health()["healthy"] is True
    # ...but far outside its own rhythm = hung.
    t[0] = 700.0
    assert hb.health()["stale"] is True
    # Before any interval exists, the flat floor applies.
    hb2 = obs_exporter.Heartbeat("x", stale_after=10.0, clock=lambda: t[0])
    hb2.beat()
    assert hb2.stale_threshold_s() == 10.0


# ---------------------------------------------------------------------------
# /snapshot + env activation
# ---------------------------------------------------------------------------


def test_snapshot_carries_registry_and_live_goodput(tmp_path):
    rec = obs.enable(str(tmp_path))
    rec.record("train_step", obs_spans.CAT_STEP, 1.0, 2.0, {"step": 0})
    rec.record("data_wait", obs_spans.CAT_DATA_WAIT, 3.0, 1.0, {"step": 1})
    obs_counters.registry().counter("steps").inc(2)
    with obs_exporter.ObsExporter(port=0) as ex:
        status, body = _get(f"http://127.0.0.1:{ex.port}/snapshot")
    assert status == 200
    snap = json.loads(body)
    assert snap["registry"]["counters"]["steps"] == 2
    # The LIVE goodput classification of the active span stream — what
    # report.py would compute post-mortem, served mid-run.
    assert snap["goodput"]["wall_s"] == 3.0
    assert snap["goodput"]["productive_s"] == 2.0
    assert snap["health"]["healthy"] is True


def test_env_port_activation(monkeypatch):
    monkeypatch.setenv("TPUDL_OBS_PORT", "0")  # ephemeral: the test idiom
    ex = obs_exporter.maybe_start_from_env()
    assert ex is not None and ex.port > 0
    assert obs_exporter.active_exporter() is ex
    # Idempotent: a second instrumented layer gets the same exporter.
    assert obs_exporter.maybe_start_from_env() is ex
    status, _ = _get(f"http://127.0.0.1:{ex.port}/metrics")
    assert status == 200

    obs_exporter.stop_exporter()
    monkeypatch.delenv("TPUDL_OBS_PORT")
    assert obs_exporter.maybe_start_from_env() is None
    monkeypatch.setenv("TPUDL_OBS_PORT", "nope")
    with pytest.raises(ValueError, match="TPUDL_OBS_PORT"):
        obs_exporter.maybe_start_from_env()


def test_env_bind_failure_warns_instead_of_killing_the_run(monkeypatch):
    """Distributor workers inherit TPUDL_OBS_PORT and a supervised
    restart can overlap its predecessor's grace window: a port
    conflict on the ENV path must degrade to a warning, never crash
    fit()/serving. An explicit start still raises."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    taken = s.getsockname()[1]
    try:
        monkeypatch.setenv("TPUDL_OBS_PORT", str(taken))
        with pytest.warns(RuntimeWarning, match="could not bind"):
            assert obs_exporter.maybe_start_from_env() is None
        with pytest.raises(OSError):
            obs_exporter.ObsExporter(port=taken).start()
    finally:
        s.close()


def test_metrics_scrape_has_no_health_side_effects():
    """/metrics is read-only: it must not evaluate health sources
    (SloMonitor.health drives burn-state transitions) — heartbeat ages
    render from the heartbeat table alone."""
    calls = []
    obs_exporter.register_health_source(
        "probe", lambda: calls.append(1) or {"healthy": True}
    )
    hb = obs_exporter.Heartbeat("train_loop")
    hb.beat()
    with obs_exporter.ObsExporter(port=0) as ex:
        _, text = _get(f"http://127.0.0.1:{ex.port}/metrics")
    assert "train_loop_heartbeat_age_s" in text
    assert calls == []


def test_histogram_mean_is_windowed_after_wrap():
    """mean sits next to the windowed min/max/percentiles and must
    describe the same window — not the cumulative series."""
    h = obs_counters.Histogram(window=4)
    for v in [1.0] * 4 + [100.0] * 4:
        h.observe(v)
    snap = h.snapshot()
    assert snap["mean"] == 100.0  # the window is all-100s now
    assert snap["count"] == 8 and snap["sum"] == 404.0  # cumulative


def test_dropped_engine_is_collectable_and_health_degrades():
    """Neither the health-source registration nor an attached
    SloMonitor's callback may pin a dropped engine's KV cache; the
    health source reports the collection gracefully."""
    import gc

    from tpudl.obs.slo import Objective, SloMonitor
    from tpudl.serve.cache import SlotCache
    from tpudl.serve.engine import Engine
    from tpudl.serve.queue import AdmissionQueue

    import jax
    import jax.numpy as jnp

    template = {
        "layer": {
            "k": jax.ShapeDtypeStruct((2, 16, 2, 4), jnp.float32),
            "valid": jax.ShapeDtypeStruct((2, 16), jnp.bool_),
            "index": jax.ShapeDtypeStruct((), jnp.int32),
        }
    }
    mon = SloMonitor([Objective("o", "serve_ttft_ms", threshold=1.0)])
    engine = Engine(
        prefill_call=lambda *a: None, decode_call=lambda *a: None,
        params=None, cache=SlotCache(template),
        queue=AdmissionQueue(capacity=4), prompt_len=4,
    )
    engine.attach_slo(mon)
    import weakref

    ref = weakref.ref(engine)
    del engine
    gc.collect()
    assert ref() is None, "engine must be collectable once dropped"
    snap = obs_exporter.health_snapshot()
    assert snap["sources"]["serve_engine"] == {
        "healthy": True, "engine": "collected",
    }
    mon.observe("serve_ttft_ms", 0.5)  # the surviving monitor still works
    assert mon.health()["healthy"] is True


def test_unknown_path_404():
    with obs_exporter.ObsExporter(port=0) as ex:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ex.port}/nope", timeout=10.0
            )
        assert ei.value.code == 404


# ---------------------------------------------------------------------------
# Live end-to-end: scraping a fit() in flight (the tier-1 smoke)
# ---------------------------------------------------------------------------


def test_fit_serves_live_metrics_and_heartbeat(tmp_path, monkeypatch):
    """The acceptance path: with TPUDL_OBS_PORT set, a running fit()
    serves /metrics (train histograms) and /healthz (ready, fresh
    train_loop heartbeat) MID-RUN — scraped from inside a logger
    callback while the loop is live."""
    import jax

    from tests.test_obs import _tiny_fit_setup
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.train import fit

    monkeypatch.setenv("TPUDL_OBS_PORT", "0")
    obs.enable(str(tmp_path / "obs"))
    state, step = _tiny_fit_setup()
    scraped = {}

    def logger(step_no, metrics):
        if scraped:
            return
        ex = obs_exporter.active_exporter()
        assert ex is not None, "fit() must start the exporter from env"
        _, scraped["metrics"] = _get(f"http://127.0.0.1:{ex.port}/metrics")
        scraped["status"], body = _get(f"http://127.0.0.1:{ex.port}/healthz")
        scraped["health"] = json.loads(body)

    state, metrics, info = fit(
        step, state,
        synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=4, num_batches=8
        ),
        jax.random.key(1),
        log_every=4,
        logger=logger,
    )
    assert info["steps"] == 8
    assert scraped["status"] == 200
    hb = scraped["health"]["heartbeats"]["train_loop"]
    assert hb["running"] is True and hb["age_s"] < 60.0
    text = scraped["metrics"]
    assert "step_time_s_count" in text
    assert "data_wait_s_count" in text
    assert any(
        l.startswith("train_last_step ") for l in text.splitlines()
    )
    # After fit returns the heartbeat reports finished, not hung.
    final = obs_exporter.health_snapshot()["heartbeats"]["train_loop"]
    assert final["running"] is False and final["healthy"] is True


# ---------------------------------------------------------------------------
# Distributor per-rank heartbeats (unit level; the slow spawn test
# exercises the live path)
# ---------------------------------------------------------------------------


def test_distributor_rank_heartbeats_from_span_file_mtime(tmp_path):
    import os
    import time as _time

    from tpudl.runtime.distributor import _update_rank_heartbeats

    workers = tmp_path / "workers"
    workers.mkdir()
    hearts = {
        pid: obs_exporter.Heartbeat(
            f"rank{pid}", stale_after=10.0, clock=_time.time
        )
        for pid in (0, 1)
    }
    t0 = _time.time()
    for hb in hearts.values():
        hb.beat_at(t0)
    # Rank 0 made progress (recent span-file mtime); rank 1 hung 100
    # virtual seconds ago.
    f0 = workers / "spans-h-p0-111.jsonl"
    f0.write_text('{"kind": "span"}\n')
    f1 = workers / "spans-h-p1-222.jsonl"
    f1.write_text('{"kind": "span"}\n')
    os.utime(f1, (t0 - 100.0, t0 - 100.0))
    reg = obs_counters.registry()
    _update_rank_heartbeats(hearts, {0, 1}, str(workers))
    assert reg.gauge("rank0_last_heartbeat_age_s").value < 5.0
    assert reg.gauge("rank1_last_heartbeat_age_s").value > 90.0
    h = obs_exporter.health_snapshot()
    assert h["heartbeats"]["rank0"]["healthy"] is True
    # The hung rank flips /healthz within one poll interval.
    assert h["heartbeats"]["rank1"]["stale"] is True
    assert h["healthy"] is False
    # Rank exits (collected): stopped, never reported hung.
    _update_rank_heartbeats(hearts, {0}, str(workers))
    h = obs_exporter.health_snapshot()
    assert h["heartbeats"]["rank1"]["running"] is False
    assert h["healthy"] is True


def test_distributor_rank_heartbeats_degrade_to_liveness_without_obs():
    """Without span recording there is no progress signal to read, so
    an alive rank's heartbeat stays fresh (process liveness) — a
    healthy obs-less cohort must never false-flip /healthz stale, no
    matter how long it runs."""
    import time as _time

    from tpudl.runtime.distributor import _update_rank_heartbeats

    hearts = {
        0: obs_exporter.Heartbeat("rank0", stale_after=10.0,
                                  clock=_time.time)
    }
    hearts[0].beat_at(_time.time() - 1000.0)  # stale launch seed
    _update_rank_heartbeats(hearts, {0}, None)  # no obs dir
    h = obs_exporter.health_snapshot()["heartbeats"]["rank0"]
    assert h["healthy"] is True and h["age_s"] < 5.0
