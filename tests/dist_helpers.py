"""Module-level functions for TpuDistributor spawn tests (must be
importable/picklable by reference from worker subprocesses)."""


def report_topology():
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def global_sum():
    """Each process contributes (process_index+1) per local device; the jitted
    global sum must see every process's contribution."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(jax.devices(), ("dp",))
    local = jnp.ones((jax.local_device_count(),)) * (jax.process_index() + 1)
    arr = multihost_utils.host_local_array_to_global_array(local, mesh, P("dp"))
    total = jax.jit(
        lambda a: a.sum(),
        in_shardings=NamedSharding(mesh, P("dp")),
        out_shardings=NamedSharding(mesh, P()),
    )(arr)
    return float(total)


def distributed_train_smoke():
    """A tiny pjit DP train run inside each spawned process — the full
    launcher -> mesh -> sharded step path (SURVEY.md §3.6) minus real ICI."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.models.resnet import ResNetTiny
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = ResNetTiny(num_classes=4)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 16, 16, 3)), optax.sgd(0.05)
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)
    # NOTE: with multiple processes each worker feeds its local shard; batches
    # here are whole-batch because local == global in this smoke (the
    # converter layer owns per-host sharding).
    losses = []
    rng = jax.random.key(1)
    for batch in synthetic_classification_batches(
        16, image_shape=(16, 16, 3), num_classes=4, num_batches=3
    ):
        import numpy as np

        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        global_batch = {
            k: multihost_utils.host_local_array_to_global_array(
                v, mesh, P(("dp", "fsdp"))
            )
            for k, v in batch.items()
        }
        state, metrics = step(state, global_batch, rng)
        losses.append(float(metrics["loss"]))
    return losses


def failing_worker():
    raise RuntimeError("intentional worker failure")


def record_obs_spans():
    """Record deterministic per-rank step spans into the worker's obs
    recorder (tpudl.runtime._worker enabled it from the TPUDL_OBS_DIR
    the distributor injected): rank 1's steps are 10x slower — the
    straggler the parent's merged report must attribute."""
    import os

    from tpudl.obs import spans as obs_spans

    rec = obs_spans.active_recorder()
    assert rec is not None, "worker obs recorder not enabled"
    rank = int(os.environ.get("TPUDL_PROCESS_ID", "0"))
    dur = 0.010 * (1 + 9 * rank)
    for i in range(5):
        rec.record(
            "train_step", obs_spans.CAT_STEP, float(i), dur, {"step": i}
        )
    return rank


def converter_fed_train(data_dir, local_batch=16):
    """The Petastorm-contract promise, actually executed multi-process
    (round-2 missing #4): each worker reads ITS disjoint converter shard
    of a materialized Parquet dataset, feeds it through
    prefetch_to_device(mesh) (jax.make_array_from_process_local_data)
    into fit(), for exactly one epoch. Returns (losses, rows_consumed)
    — ranks must agree on every global loss, and the rows consumed
    across ranks must cover the dataset (minus batch truncation)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.data.converter import make_converter
    from tpudl.data.datasets import device_normalize_cifar, wire_cifar_batch
    from tpudl.data.prefetch import prefetch_to_device
    from tpudl.models.resnet import ResNetTiny
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        fit,
        make_classification_train_step,
    )

    conv = make_converter(data_dir)
    mesh = make_mesh(MeshSpec(dp=-1))
    model = ResNetTiny(num_classes=10)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 32, 32, 3)), optax.sgd(0.05)
    )
    # Wire dtype stays uint8 across the process-local -> global-array
    # boundary; normalization happens device-side inside the step.
    step = compile_step(
        make_classification_train_step(
            input_transform=device_normalize_cifar()
        ),
        mesh, state, None,
    )

    rows = {"n": 0}

    def counted():
        for batch in conv.make_batch_iterator(
            local_batch,
            epochs=1,
            shuffle=False,
            drop_last=True,
            shard_index=jax.process_index(),
            num_shards=jax.process_count(),
        ):
            rows["n"] += len(batch["label"])
            yield batch

    losses = []

    def log(i, metrics):
        losses.append(metrics["loss"])

    state, metrics, info = fit(
        step,
        state,
        prefetch_to_device(
            counted(), mesh=mesh, transform=wire_cifar_batch,
            assembly_workers=2,
        ),
        jax.random.key(1),
        log_every=1,
        logger=log,
    )
    return losses, rows["n"]


def prefetch_multicolumn_global(local_batch=8, num_batches=6):
    """Multi-column batches through the TWO-STAGE prefetch's multi-host
    path (jax.make_array_from_process_local_data): every rank feeds
    uint8 image + int32 label + float32 weight columns and reports the
    GLOBAL shapes, dtypes, per-column global sums, and the order marker
    column — ranks must agree on all of them (each rank addresses only
    its shard; the sums force a cross-process reduction)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudl.data.prefetch import prefetch_to_device

    rank = jax.process_index()

    def batches():
        for i in range(num_batches):
            base = i * 1000 + rank * 100
            yield {
                "image": np.full(
                    (local_batch, 4, 4, 3), i + 1, dtype=np.uint8
                ),
                "label": np.full((local_batch,), base, dtype=np.int32),
                "weight": np.full((local_batch,), float(i), np.float32),
                "order": np.full((local_batch,), i, dtype=np.int32),
            }

    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudl.runtime.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(dp=-1))
    # Explicit shardings: the localhost multi-process CPU backend only
    # runs cross-process computations through pjit-annotated programs.
    sum_fn = jax.jit(
        lambda b: {k: jnp.sum(b[k].astype(jnp.float32)) for k in b},
        in_shardings=NamedSharding(mesh, P(("dp", "fsdp"))),
        out_shardings=NamedSharding(mesh, P()),
    )
    out = []
    for gb in prefetch_to_device(batches(), mesh=mesh, assembly_workers=3):
        summed = sum_fn(gb)
        out.append(
            {
                "shapes": {k: tuple(v.shape) for k, v in gb.items()},
                "dtypes": {k: str(v.dtype) for k, v in gb.items()},
                "sums": {k: float(v) for k, v in summed.items()},
                "order": int(np.asarray(gb["order"].addressable_data(0))[0]),
            }
        )
    return out


def _ckpt_state():
    """Deterministic tiny state with BatchNorm stats AND momentum — both
    must round-trip through the multi-process checkpoint."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.models.resnet import ResNetTiny
    from tpudl.train import create_train_state

    model = ResNetTiny(num_classes=4)
    return create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.05, momentum=0.9),
    )


def _ckpt_batches(n):
    """Seeded global batch stream — every process regenerates the same
    sequence, so 'fast-forward past the consumed steps' is list slicing."""
    from tpudl.data.synthetic import synthetic_classification_batches

    return list(
        synthetic_classification_batches(
            16, image_shape=(16, 16, 3), num_classes=4, num_batches=n, seed=7
        )
    )


def _ckpt_train(state, step, mesh, batches, rng):
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    losses = []
    for b in batches:
        gb = {
            k: multihost_utils.host_local_array_to_global_array(
                v, mesh, P(("dp", "fsdp"))
            )
            for k, v in b.items()
        }
        state, m = step(state, gb, rng)
        losses.append(float(m["loss"]))
    return state, losses


def checkpoint_save_phase(ckpt_dir, steps=3):
    """Phase 1 of the multi-process recovery story: train, save via
    CheckpointManager from EVERY rank (Orbax coordinates the write across
    processes), drain, exit — the 'kill' is the process exit itself."""
    import jax

    from tpudl.checkpoint import CheckpointManager
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import compile_step, make_classification_train_step

    state = _ckpt_state()
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)
    state, losses = _ckpt_train(
        state, step, mesh, _ckpt_batches(steps), jax.random.key(1)
    )
    with CheckpointManager(ckpt_dir) as mgr:
        mgr.save(steps, state)
        mgr.wait_until_finished()
    return jax.process_index(), losses


def checkpoint_resume_phase(ckpt_dir, total_steps=5, saved_steps=3):
    """Phase 2 (a FRESH spawn): restore on every rank (sharding-aware,
    mesh-placed), train the remaining batches, and also run an
    uninterrupted from-scratch control — the post-resume losses must
    equal the control's tail exactly (the train step folds the rng with
    state.step, which the checkpoint carries)."""
    import jax

    from tpudl.checkpoint import CheckpointManager
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train import compile_step, make_classification_train_step

    template = _ckpt_state()
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, template, None)
    with CheckpointManager(ckpt_dir) as mgr:
        restored_step = mgr.latest_step()
        state = mgr.restore(template, mesh=mesh, rules=None)
    batches = _ckpt_batches(total_steps)
    state, resumed = _ckpt_train(
        state, step, mesh, batches[saved_steps:], jax.random.key(1)
    )
    control_state = _ckpt_state()
    control_state, control = _ckpt_train(
        control_state, step, mesh, batches, jax.random.key(1)
    )
    return jax.process_index(), int(restored_step), resumed, control
