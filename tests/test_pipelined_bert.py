"""Pipeline-parallel BERT training end-to-end (tpudl.parallel.pipelined_bert).

The round-2 verdict's acceptance: tiny-BERT training under pp=4 must
match pp=1 losses step for step, driven through the REAL training stack
(create_train_state / compile_step / fit semantics), with optimizer state
living over the stacked stage tree.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpudl.models.bert import BERT_TINY
from tpudl.parallel.pipelined_bert import (
    PIPELINED_BERT_RULES,
    PipelinedBertClassifier,
)
from tpudl.parallel.sharding import _path_str
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    make_classification_train_step,
)

CFG = BERT_TINY(
    num_layers=4,
    vocab_size=256,
    num_heads=2,
    dtype=jnp.float32,  # isolate schedule parity from bf16 rounding
)


def _batches(n, batch=16, seq=16, seed=5):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(1, 256, size=(batch, seq)).astype(np.int32)
        out.append(
            {
                "input_ids": ids,
                "attention_mask": np.ones_like(ids),
                "label": rng.integers(0, 2, size=(batch,)).astype(np.int32),
            }
        )
    return out


def _train(mesh, steps=6, cfg=None, distinct_batches=2,
           param_fsdp=False, num_stages=4, virtual_stages=1):
    from tpudl.parallel.pipelined_bert import PIPELINED_BERT_FSDP_RULES

    model = PipelinedBertClassifier(
        cfg or CFG, num_stages=num_stages, num_microbatches=4,
        param_fsdp=param_fsdp, virtual_stages=virtual_stages,
    )
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 16), jnp.int32),
        optax.adamw(1e-3),
    )
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh,
        state,
        PIPELINED_BERT_FSDP_RULES if param_fsdp else PIPELINED_BERT_RULES,
    )
    losses = []
    rng = jax.random.key(1)
    # A small cycling batch set, so "it learns" is memorization-testable.
    pool = _batches(distinct_batches)
    for i in range(steps):
        state, metrics = step(state, pool[i % distinct_batches], rng)
        losses.append(float(metrics["loss"]))
    return losses, step, state


NODROP = BERT_TINY(
    num_layers=4,
    vocab_size=256,
    num_heads=2,
    hidden_dropout=0.0,
    attention_dropout=0.0,
    dtype=jnp.float32,
)


def test_pp4_training_matches_pp1():
    """Same model, same data, same rngs: losses under the pp=4 pipeline
    equal the pp=1 sequential fold step for step (dropout off — the
    deterministic-math acceptance; see the module docstring for why
    dropout STREAMS legitimately differ across mesh layouts).

    Tolerances: the first step is strict (identical math); the 8-device
    meshes necessarily differ in data-parallel extent (pp=1 forces dp=8,
    pp=4 runs dp=2), so f32 psum-order noise amplifies mildly through
    AdamW over later steps — the trajectory bound still catches real
    schedule bugs (those diverge O(1) immediately)."""
    pp1_losses, _, _ = _train(
        make_mesh(MeshSpec(dp=-1, pp=1)), steps=10, cfg=NODROP
    )
    pp4_losses, _, _ = _train(
        make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4)),
        steps=10,
        cfg=NODROP,
    )
    np.testing.assert_allclose(pp4_losses[0], pp1_losses[0], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(pp4_losses, pp1_losses, rtol=1e-3, atol=1e-3)
    # and it actually learns (memorizes the cycling batch pool)
    assert min(pp4_losses[-2:]) < pp4_losses[0]


def test_pp4_trains_with_dropout():
    """Dropout-0.1 training under pp=4: deterministic per rng, finite,
    learning — the masks are per-(microbatch, layer) streams from the
    hardware-bits path."""
    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4))
    losses_a, _, _ = _train(mesh, steps=12)
    losses_b, _, _ = _train(mesh, steps=12)
    np.testing.assert_allclose(losses_a, losses_b, rtol=0, atol=0)
    assert np.all(np.isfinite(losses_a))
    assert min(losses_a[-2:]) < losses_a[0]


def test_stage_params_and_opt_state_shard_over_pp():
    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4))
    _, step, state = _train(mesh, steps=1)
    specs = {
        _path_str(p): str(s.spec)
        for p, s in jax.tree_util.tree_leaves_with_path(step.state_shardings)
    }
    stage_param_specs = [
        s
        for p, s in specs.items()
        if "stages/layers" in p and p.startswith("params/")
    ]
    assert stage_param_specs and all("pp" in s for s in stage_param_specs)
    # optimizer moments over the stacked tree shard too
    opt_specs = [
        s
        for p, s in specs.items()
        if "stages/layers" in p and "opt_state" in p and "kernel" in p
    ]
    assert opt_specs and all("pp" in s for s in opt_specs), specs


def test_forward_matches_sequential_layers():
    """The pipelined forward (no mesh: degenerate fold) equals manually
    running embeddings -> layers -> pooler -> classifier with the same
    restructured weights."""
    from tpudl.models.bert import BertEmbeddings, BertLayer, _dense
    from tpudl.ops.attention import padding_mask

    model = PipelinedBertClassifier(CFG, num_stages=2, num_microbatches=2)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, 256, size=(4, 16)), jnp.int32
    )
    variables = model.init(jax.random.key(3), ids)
    out = model.apply(variables, ids)

    p = variables["params"]
    x = BertEmbeddings(CFG).apply(
        {"params": p["io"]["embeddings"]}, ids, jnp.zeros_like(ids), False
    )
    mask4 = padding_mask(jnp.ones_like(ids))
    layer = BertLayer(CFG)
    stacked = p["stages"]["layers"]
    for s in range(2):
        for j in range(2):
            lp = jax.tree.map(lambda a: a[s][j], stacked)
            x = layer.apply({"params": lp}, x, mask4, False)
    pooled = jnp.tanh(
        _dense(CFG, CFG.hidden_size, "pooler").apply(
            {"params": p["io"]["pooler"]}, x[:, 0]
        )
    )
    expected = (
        pooled @ p["io"]["classifier"]["kernel"]
        + p["io"]["classifier"]["bias"]
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=1e-5
    )


def test_validates_divisibility():
    import pytest

    with pytest.raises(ValueError, match="not divisible"):
        PipelinedBertClassifier(CFG, num_stages=3, num_microbatches=2)
    model = PipelinedBertClassifier(CFG, num_stages=2, num_microbatches=3)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="num_microbatches"):
        model.apply(variables, jnp.zeros((4, 8), jnp.int32))


def test_pp_fsdp_training_matches_pp1():
    """pp=4 x fsdp=2 (ZeRO-in-pipeline: stage weights + moments sharded
    1/(pp*fsdp), all-gathered per step inside the shard_map) trains to
    the same losses as the pp=1 sequential fold — the round-4 VERDICT
    composition acceptance."""
    pp1_losses, _, _ = _train(
        make_mesh(MeshSpec(dp=-1, pp=1)), steps=10, cfg=NODROP
    )
    losses, _, _ = _train(
        make_mesh(MeshSpec(dp=1, fsdp=2, sp=1, tp=1, pp=4)),
        steps=10,
        cfg=NODROP,
        param_fsdp=True,
    )
    np.testing.assert_allclose(losses[0], pp1_losses[0], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(losses, pp1_losses, rtol=1e-3, atol=1e-3)
    assert min(losses[-2:]) < losses[0]


def test_pp_fsdp_state_sharded_over_both_axes():
    """Anti-decorativeness: with strategy pp+fsdp the stage KERNELS (and
    their AdamW moments) carry BOTH mesh axes in their sharding specs,
    and matrix leaves are genuinely split 1/(pp*fsdp) per device."""
    mesh = make_mesh(MeshSpec(dp=1, fsdp=2, sp=1, tp=1, pp=4))
    _, step, state = _train(mesh, steps=1, param_fsdp=True)
    specs = {
        _path_str(p): str(s.spec)
        for p, s in jax.tree_util.tree_leaves_with_path(step.state_shardings)
    }
    kernel_specs = [
        s for p, s in specs.items()
        if "stages/layers" in p and p.endswith("kernel")
    ]
    assert kernel_specs
    assert all("pp" in s and "fsdp" in s for s in kernel_specs), specs
    opt_specs = [
        s for p, s in specs.items()
        if "stages/layers" in p and "opt_state" in p and p.endswith("kernel")
    ]
    assert opt_specs and all(
        "pp" in s and "fsdp" in s for s in opt_specs
    ), specs
    # an actual kernel leaf is split over both axes on device
    kernels = [
        leaf for path, leaf in jax.tree_util.tree_leaves_with_path(
            state.params
        )
        if _path_str(path).endswith("kernel") and "layers" in _path_str(path)
    ]
    leaf = kernels[0]
    shard_size = leaf.addressable_shards[0].data.size
    assert shard_size * 8 == leaf.size, (shard_size, leaf.size)


def test_interleaved_pp2_v2_training_matches_pp1():
    """virtual_stages=2 on a pp=2 mesh (4 layers as 4 round-robin chunks,
    2 per device): the interleaved schedule's losses equal the pp=1
    sequential fold step for step (dropout off), and it learns — the
    lower-bubble schedule is drivable through the SAME train stack."""
    pp1, _, _ = _train(
        make_mesh(MeshSpec(dp=-1, pp=1)), steps=10, cfg=NODROP,
        num_stages=1, virtual_stages=4,
    )
    ppi, _, _ = _train(
        make_mesh(MeshSpec(dp=2, fsdp=2, sp=1, tp=1, pp=2)), steps=10,
        cfg=NODROP, num_stages=2, virtual_stages=2,
    )
    np.testing.assert_allclose(ppi[0], pp1[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ppi, pp1, rtol=1e-3, atol=1e-3)
    assert min(ppi[-2:]) < ppi[0]


def test_interleaved_trains_with_dropout_and_shards_over_pp():
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, sp=1, tp=1, pp=2))
    losses_a, step, _ = _train(mesh, steps=8, num_stages=2,
                               virtual_stages=2)
    losses_b, _, _ = _train(mesh, steps=8, num_stages=2, virtual_stages=2)
    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-6)
    assert min(losses_a[-2:]) < losses_a[0]
    pp_sharded = [
        _path_str(path)
        for path, sh in jax.tree_util.tree_leaves_with_path(
            step.state_shardings
        )
        if "pp" in str(sh.spec)
    ]
    assert any("stages" in p and "params" in p for p in pp_sharded)
    assert any("opt_state" in p for p in pp_sharded)


def test_interleaved_validates():
    import pytest

    with pytest.raises(ValueError, match="param_fsdp"):
        PipelinedBertClassifier(CFG, num_stages=2, num_microbatches=2,
                                param_fsdp=True, virtual_stages=2)
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedBertClassifier(CFG, num_stages=2, num_microbatches=2,
                                virtual_stages=3)
