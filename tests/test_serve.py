"""Continuous-batching serving engine (tpudl.serve).

The correctness bar mirrors test_generate's: every request served
through the slot engine — whatever its neighbors, seat time, refills,
or horizon rollovers — must produce token-for-token what ``generate()``
produces for that request alone, through both the live model and the
deserialized StableHLO artifact pair. On top of that: admission
rejects the unservable, deadlines shed the late, and continuous
batching measurably beats run-to-completion static batching on ragged
workloads (asserted on the DETERMINISTIC decode-step count here;
benchmarks/serve_load.py carries the wall-clock claim in the slow
tier).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.serve import (
    AdmissionQueue,
    PagedKVCache,
    Request,
    ServeSession,
    SlotCache,
    assert_serving_parity,
)

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8
SLOTS = 4


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def _session(model, params, **kw):
    kw.setdefault("prompt_len", PROMPT_LEN)
    kw.setdefault("num_slots", SLOTS)
    return ServeSession.from_model(model, params, **kw)


def _ragged_requests(n, seed=0, max_new_lo=4, max_new_hi=20, **kw):
    rng = np.random.default_rng(seed)
    return [
        Request(
            request_id=f"r{i}",
            input_ids=rng.integers(
                1, CFG.vocab_size, size=int(rng.integers(2, PROMPT_LEN + 1))
            ).tolist(),
            max_new_tokens=int(rng.integers(max_new_lo, max_new_hi)),
            **kw,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# Tier-1 smoke: the satellite-specified config (tiny Llama, 4 slots,
# 8 requests) through the whole stack.
# ---------------------------------------------------------------------------


def test_smoke_continuous_serving(model_and_params):
    model, params = model_and_params
    session = _session(model, params)
    requests = _ragged_requests(8, seed=1)
    assert_serving_parity(session, model, params, requests)
    assert session.engine.num_prefills == 8  # every request was seated
    assert session.engine.num_decode_steps > 0


def test_results_carry_timing_and_reasons(model_and_params):
    model, params = model_and_params
    session = _session(model, params)
    results = session.serve(_ragged_requests(6, seed=2))
    assert len(results) == 6
    for res in results.values():
        assert res.finish_reason == "length"  # no eos configured
        assert res.ttft_s is not None and res.ttft_s >= 0
        # Queue wait ends at seating; TTFT adds the prefill on top.
        assert res.queue_wait_s is not None
        assert res.queue_wait_s <= res.ttft_s
        assert len(res.tokens) > 1 and res.tpot_s is not None


# ---------------------------------------------------------------------------
# Edge cases the ISSUE names.
# ---------------------------------------------------------------------------


def test_refill_on_exact_step_neighbor_emits_eos(model_and_params):
    """The moment slot A emits EOS, the waiting request is seated into
    it — while slot B keeps decoding mid-stream. Neither B nor the
    newcomer may be perturbed (bit-exact vs. each alone)."""
    model, params = model_and_params
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(1, CFG.vocab_size, size=5).tolist() for _ in range(3)
    ]
    # Probe greedily to find an eos that request A emits mid-stream.
    probe = generate(
        model, params, jnp.asarray(prompts[0])[None, :], max_new_tokens=20
    )
    eos = int(probe[0, 4])  # A finishes the step it produces token 5
    requests = [
        Request("A", prompts[0], max_new_tokens=20, eos_id=eos),
        Request("B", prompts[1], max_new_tokens=24),
        Request("C", prompts[2], max_new_tokens=8),  # seated on A's eos
    ]
    session = _session(model, params, num_slots=2)
    results = session.serve(requests)
    assert results["A"].finish_reason == "eos"
    assert results["A"].tokens[-1] == eos and len(results["A"].tokens) <= 20
    # C was refilled mid-stream: the engine never drained between A and
    # C (a drain would show as a rollover or an idle gap; prefills == 3
    # with decode steps bounded by B's runtime shows overlap).
    assert session.engine.num_prefills == 3
    assert session.engine.num_decode_steps < (20 + 24 + 8 - 3)
    for req in requests:
        want = np.asarray(
            generate(
                model, params, jnp.asarray(req.input_ids)[None, :],
                max_new_tokens=req.max_new_tokens, eos_id=req.eos_id,
            )
        )[0]
        got = np.asarray(results[req.request_id].tokens)
        np.testing.assert_array_equal(
            got, want[: got.shape[0]], err_msg=req.request_id
        )


def test_queue_timeout_shedding(model_and_params):
    """A request whose deadline passes before it is seated is shed with
    finish_reason=shed_timeout; running requests are never aborted."""
    model, params = model_and_params
    t = [0.0]
    session = _session(model, params, num_slots=2, clock=lambda: t[0])
    session.submit(Request("late", [1, 2, 3], max_new_tokens=4,
                           deadline_s=1.0))
    t[0] = 5.0  # deadline passed while queued
    session.submit(Request("ok", [1, 2, 3], max_new_tokens=4))
    results = session.collect()
    assert results["late"].finish_reason == "shed_timeout"
    assert results["late"].tokens == []
    assert results["ok"].finish_reason == "length"


def test_admission_rejects(model_and_params):
    model, params = model_and_params
    session = _session(model, params, num_slots=2)
    with pytest.raises(ValueError, match="prompt window"):
        session.submit(
            Request("long", list(range(1, PROMPT_LEN + 2)), max_new_tokens=2)
        )
    with pytest.raises(ValueError, match="max_seq_len"):
        session.submit(
            Request("huge", [1, 2], max_new_tokens=CFG.max_seq_len)
        )
    with pytest.raises(ValueError, match="at least one token"):
        session.submit(Request("empty", [], max_new_tokens=2))
    with pytest.raises(ValueError, match="max_new_tokens"):
        session.submit(Request("zero", [1], max_new_tokens=0))
    with pytest.raises(ValueError, match="uint32"):
        # Seeds ride as uint32 in the engine; out-of-range must fail at
        # admission, not mid-serving (which would strand the batch).
        session.submit(Request("neg", [1], max_new_tokens=2, seed=-1))
    session.submit(Request("dup", [1, 2], max_new_tokens=2))
    with pytest.raises(ValueError, match="duplicate"):
        session.submit(Request("dup", [1, 2], max_new_tokens=2))
    results = session.collect()
    assert results["dup"].ok


def test_queue_capacity_sheds(model_and_params):
    model, params = model_and_params
    session = _session(model, params, num_slots=2, queue_capacity=2)
    for i in range(4):
        session.submit(Request(f"q{i}", [1, 2], max_new_tokens=3))
    results = session.collect()
    reasons = sorted(r.finish_reason for r in results.values())
    assert reasons == ["length", "length", "shed_capacity", "shed_capacity"]


def test_artifact_vs_live_parity(model_and_params, tmp_path):
    """A ServeSession fed the StableHLO artifact pair produces
    token-for-token the same outputs as the live model — and as
    generate() — for the same seeds, through files on disk."""
    from tpudl.export.decode import export_serving_decoder

    model, params = model_and_params
    prefix = str(tmp_path / "serve_tiny")
    export_serving_decoder(
        model, params, num_slots=SLOTS, prompt_len=PROMPT_LEN,
        path_prefix=prefix,
    )
    art = ServeSession.from_artifacts(
        f"{prefix}.prefill.stablehlo", f"{prefix}.decode.stablehlo", params
    )
    assert (art.num_slots, art.prompt_len, art.max_seq_len) == (
        SLOTS, PROMPT_LEN, CFG.max_seq_len,
    )
    # Mixed greedy + sampled workload, same seeds through both backends.
    requests = _ragged_requests(8, seed=4)
    for i, req in enumerate(requests):
        if i % 3 == 0:
            req.temperature = 0.8
            req.seed = 100 + i
    live = _session(model, params)
    r_live = live.serve([Request(**r.__dict__) for r in requests])
    r_art = art.serve([Request(**r.__dict__) for r in requests])
    for rid in r_live:
        assert r_live[rid].tokens == r_art[rid].tokens, rid
    # Greedy requests additionally match live generate() run alone.
    for req in requests:
        if req.temperature:
            continue
        want = np.asarray(
            generate(
                model, params, jnp.asarray(req.input_ids)[None, :],
                max_new_tokens=req.max_new_tokens,
            )
        )[0]
        np.testing.assert_array_equal(
            np.asarray(r_live[req.request_id].tokens),
            want[: len(r_live[req.request_id].tokens)],
        )


def test_horizon_rollover_preserves_parity(model_and_params):
    """More queued decode work than one cache horizon holds: the engine
    rolls the cache over between waves and every request still matches
    its solo generation."""
    model = LlamaForCausalLM(LLAMA_TINY(dtype=jnp.float32, max_seq_len=32))
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2
    )
    rng = np.random.default_rng(5)
    requests = [
        Request(f"r{i}", rng.integers(1, 500, size=5).tolist(),
                max_new_tokens=20)
        for i in range(5)
    ]
    results = session.serve(requests)
    assert session.engine.num_rollovers >= 1
    # The host-mirrored write index stayed in lockstep with the
    # device-side scalar through seats, decode steps, and resets.
    device_index = next(
        int(leaf)
        for leaf in jax.tree.leaves(session.engine.cache.cache)
        if leaf.ndim == 0
    )
    assert device_index == session.engine.cache.write_index
    for req in requests:
        want = np.asarray(
            generate(model, params, jnp.asarray(req.input_ids)[None, :],
                     max_new_tokens=20)
        )[0]
        np.testing.assert_array_equal(
            np.asarray(results[req.request_id].tokens), want
        )


def test_sampling_is_batch_composition_independent(model_and_params):
    """Token t of a sampled request draws from fold_in(key(seed), t):
    the same request yields the same tokens served alone or in a full
    ragged batch — reproducibility generate()'s shared rng stream
    cannot offer."""
    model, params = model_and_params
    req = Request("s", [7, 8, 9], max_new_tokens=10, temperature=1.0, seed=42)
    alone = _session(model, params).serve([Request(**req.__dict__)])
    crowd_reqs = [Request(**req.__dict__)] + _ragged_requests(6, seed=6)
    crowd = _session(model, params).serve(crowd_reqs)
    assert alone["s"].tokens == crowd["s"].tokens
    # And a different seed actually changes the stream.
    other = Request("s", [7, 8, 9], max_new_tokens=10, temperature=1.0,
                    seed=43)
    r_other = _session(model, params).serve([other])
    assert r_other["s"].tokens != alone["s"].tokens


def test_continuous_beats_static_on_decode_steps(model_and_params):
    """The acceptance ratio on its deterministic basis: equal slots,
    ragged lengths, the SAME engine with mid-stream refill on vs off —
    continuous must finish the workload in >= 1.3x fewer decode steps
    (wall-clock tokens/sec rides this 1:1 at fixed slot count; the slow
    tier asserts the timed version via benchmarks/serve_load.py)."""
    model, params = model_and_params
    lengths = [40, 6, 6, 6, 40, 6, 6, 6]
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 500, size=5).tolist() for _ in lengths]

    def reqs():
        return [
            Request(f"r{i}", prompts[i], max_new_tokens=n)
            for i, n in enumerate(lengths)
        ]

    cont = _session(model, params)
    r_cont = cont.serve(reqs())
    stat = _session(model, params, continuous=False)
    r_stat = stat.serve(reqs())
    assert all(r.ok for r in r_cont.values())
    # Identical tokens either way — batching policy is invisible to
    # outputs, it only moves time.
    for rid in r_cont:
        assert r_cont[rid].tokens == r_stat[rid].tokens, rid
    ratio = stat.engine.num_decode_steps / cont.engine.num_decode_steps
    assert ratio >= 1.3, (
        f"continuous batching only {ratio:.2f}x fewer decode steps than "
        f"static (cont={cont.engine.num_decode_steps}, "
        f"stat={stat.engine.num_decode_steps})"
    )


def test_serve_obs_flow(model_and_params):
    """Engine metrics land in the obs registry: busy gauge, TTFT/TPOT
    histograms, completion counters, cache byte accounting."""
    from tpudl.obs import registry

    model, params = model_and_params
    reg = registry()
    completed0 = reg.counter("serve_requests_completed").value
    prefills0 = reg.counter("serve_prefills").value
    ttft0 = reg.histogram("serve_ttft_ms").count
    session = _session(model, params, num_slots=2)
    session.serve(_ragged_requests(4, seed=8))
    assert reg.counter("serve_requests_completed").value == completed0 + 4
    assert reg.counter("serve_prefills").value == prefills0 + 4
    assert reg.histogram("serve_ttft_ms").count == ttft0 + 4
    assert reg.gauge("serve_slots_busy").value == 0  # drained
    assert reg.gauge("serve_cache_bytes").value > 0


# ---------------------------------------------------------------------------
# Queue and cache units (host-only, no model).
# ---------------------------------------------------------------------------


def test_admission_queue_priority_fifo_and_fit():
    t = [0.0]
    q = AdmissionQueue(capacity=8, clock=lambda: t[0])

    class R:
        def __init__(self, name, size=1):
            self.name, self.size = name, size

    assert q.push(R("b0"), priority=1)
    assert q.push(R("a0"), priority=0)
    assert q.push(R("a1"), priority=0)
    assert q.push(R("big", size=99), priority=0)
    # Priority first, FIFO within priority, fit-filter skips without
    # reordering what it skips.
    entry, shed = q.pop(fit=lambda r: r.size < 10)
    assert entry.request.name == "a0" and not shed
    entry, _ = q.pop(fit=lambda r: r.size < 10)
    assert entry.request.name == "a1"
    entry, _ = q.pop(fit=lambda r: r.size < 10)
    assert entry.request.name == "b0"  # "big" skipped, still queued
    assert len(q) == 1
    entry, _ = q.pop()
    assert entry.request.name == "big"


def test_admission_queue_deadlines_and_capacity():
    t = [0.0]
    q = AdmissionQueue(capacity=2, clock=lambda: t[0])
    assert q.push("x", deadline_s=1.0)
    assert q.push("y")
    assert not q.push("overflow")  # bounded
    t[0] = 2.0
    entry, shed = q.pop()
    assert entry.request == "y"  # x expired on the way
    assert [e.request for e in shed] == ["x"]
    q.push("z", deadline_s=0.5)
    t[0] = 9.0
    assert [e.request for e in q.drain_expired()] == ["z"]
    assert len(q) == 0
    with pytest.raises(ValueError, match="capacity"):
        AdmissionQueue(capacity=0)


def test_slot_cache_bookkeeping():
    template = {
        "layer": {
            "k": jax.ShapeDtypeStruct((3, 16, 2, 4), jnp.float32),
            "valid": jax.ShapeDtypeStruct((3, 16), jnp.bool_),
            "index": jax.ShapeDtypeStruct((), jnp.int32),
        }
    }
    cache = SlotCache(template)
    assert (cache.num_slots, cache.max_seq_len) == (3, 16)
    assert cache.write_index == 0 and cache.remaining_horizon == 16
    row = {
        "layer": {
            "k": jnp.ones((1, 16, 2, 4), jnp.float32),
            "valid": jnp.asarray([[True] * 5 + [False] * 11]),
            "index": jnp.int32(5),
        }
    }
    cache.insert(row, 1)
    assert cache.write_index == 0  # row's own index never leaks in
    np.testing.assert_array_equal(cache.valid_counts(), [0, 5, 0])
    cache.set_write_index(5)
    assert cache.write_index == 5 and cache.remaining_horizon == 11
    cache.free(1)
    np.testing.assert_array_equal(cache.valid_counts(), [0, 0, 0])
    assert cache.write_index == 5  # free touches validity only
    cache.advance_write_index()  # host mirror of one decode dispatch
    assert cache.write_index == 6 and cache.remaining_horizon == 10
    cache.reset()
    assert cache.write_index == 0
    assert cache.nbytes > 0
    with pytest.raises(IndexError):
        cache.insert(row, 3)
    with pytest.raises(ValueError, match="validity"):
        SlotCache({"k": jax.ShapeDtypeStruct((3, 16), jnp.float32)})


def test_admission_queue_starvation_promotion():
    """The aged-FIFO guard: a low-priority entry that has waited past
    promote_after_s is served next regardless of the high-priority
    stream still arriving — bounded wait instead of starving forever."""
    t = [0.0]
    q = AdmissionQueue(capacity=8, clock=lambda: t[0], promote_after_s=5.0)
    assert q.push("low", priority=9)
    assert q.push("hi0", priority=0)
    entry, _ = q.pop()
    assert entry.request == "hi0"  # not aged yet: priority order holds
    t[0] = 6.0  # "low" has now waited past the promotion bound
    q.push("hi1", priority=0)
    entry, _ = q.pop()
    assert entry.request == "low"  # aged FIFO promotion
    entry, _ = q.pop()
    assert entry.request == "hi1"
    assert len(q) == 0

    # An aged head that fails the fit filter doesn't block normal pops.
    class R:
        def __init__(self, name, big=False):
            self.name, self.big = name, big

    q.push(R("big-old", big=True), priority=9)
    t[0] += 6.0
    q.push(R("small"), priority=0)
    entry, _ = q.pop(fit=lambda r: not r.big)
    assert entry.request.name == "small"

    # promote_after_s=None disables promotion entirely.
    t2 = [0.0]
    q2 = AdmissionQueue(capacity=8, clock=lambda: t2[0],
                        promote_after_s=None)
    q2.push("low", priority=9)
    t2[0] = 1e9
    q2.push("hi", priority=0)
    entry, _ = q2.pop()
    assert entry.request == "hi"
    with pytest.raises(ValueError, match="promote_after_s"):
        AdmissionQueue(promote_after_s=0)


def test_admission_queue_deadline_heap_and_lazy_deletion():
    """Expiry comes off the dedicated deadline min-heap (O(expired log
    n), not a full scan) with lazy deletion: entries consumed through
    one index never resurface through another."""
    t = [0.0]
    q = AdmissionQueue(capacity=16, clock=lambda: t[0])
    q.push("a", deadline_s=1.0)
    q.push("b", deadline_s=2.0)
    q.push("c", deadline_s=3.0)
    q.push("d")
    entry, shed = q.pop()
    assert entry.request == "a" and not shed  # popped before expiry
    t[0] = 2.5  # a is consumed, b expired: only b sheds
    entry, shed = q.pop()
    assert entry.request == "c"
    assert [e.request for e in shed] == ["b"]
    assert len(q) == 1  # just d
    # drain_all hands back scheduling order and empties EVERY index —
    # no stale entry sheds later from the deadline heap or FIFO.
    q.push("e", priority=1, deadline_s=9.0)
    q.push("f", priority=0)
    assert [e.request for e in q.drain_all()] == ["d", "f", "e"]
    assert len(q) == 0
    t[0] = 1e9
    assert q.drain_expired() == []
    assert q.pop() == (None, [])


# ---------------------------------------------------------------------------
# Paged + quantized KV cache.
# ---------------------------------------------------------------------------


def _paged_template(num_slots=2, seq=32, hkv=2, hd=4):
    shape = jax.ShapeDtypeStruct
    return {
        "layer": {
            "k": shape((num_slots, seq, hkv, hd), jnp.float32),
            "v": shape((num_slots, seq, hkv, hd), jnp.float32),
            "valid": shape((num_slots, seq), jnp.bool_),
            "index": shape((), jnp.int32),
        }
    }


def _paged_row(seq=32, hkv=2, hd=4, fill=1.0):
    return {
        "layer": {
            "k": jnp.full((1, seq, hkv, hd), fill, jnp.float32),
            "v": jnp.full((1, seq, hkv, hd), -fill, jnp.float32),
            "valid": jnp.ones((1, seq), jnp.bool_),
            "index": jnp.int32(8),
        }
    }


def test_paged_cache_seating_and_reservation():
    cache = PagedKVCache(_paged_template(), page_size=8)
    assert (cache.num_slots, cache.max_seq_len) == (2, 32)
    assert cache.pages_per_slot == 4
    assert cache.free_pages == 8  # 2 slots x 4 pages; page 0 is trash
    assert cache.fits_tokens(64) and not cache.fits_tokens(65)
    cache.seat(_paged_row(), 0, pad=2, prompt_len=8, reserve_tokens=16)
    assert cache.free_pages == 6  # ceil(16 / 8) = 2 pages reserved
    assert cache.page_table[0, 0] != 0  # mapped off the trash page
    assert (cache.start[0], cache.lens[0]) == (2, 8)
    # The prompt region actually landed in the mapped page.
    page = int(cache.page_table[0, 0])
    assert float(
        jnp.abs(cache.cache["layer"]["pages_k"][page]).sum()
    ) > 0
    with pytest.raises(ValueError, match="already seated"):
        cache.seat(_paged_row(), 0, pad=0, prompt_len=8, reserve_tokens=8)
    with pytest.raises(ValueError, match="exceeds the logical"):
        cache.seat(_paged_row(), 1, pad=0, prompt_len=8, reserve_tokens=33)
    cache.advance([0])
    assert cache.lens[0] == 9
    cache.free(0)
    assert cache.free_pages == 8
    assert (cache.page_table[0] == 0).all()  # back on the trash page
    assert cache.lens[0] == 0
    # Exhaustion raises when admission is bypassed (fits_tokens is the
    # predicate that makes this unreachable in the engine).
    small = PagedKVCache(_paged_template(), page_size=8, num_pages=6)
    small.seat(_paged_row(), 0, pad=0, prompt_len=8, reserve_tokens=32)
    assert small.free_pages == 1
    assert not small.fits_tokens(16)
    with pytest.raises(RuntimeError, match="exhausted"):
        small.seat(_paged_row(), 1, pad=0, prompt_len=8, reserve_tokens=16)
    small.reset()
    assert small.free_pages == 5
    with pytest.raises(ValueError, match="page_size"):
        PagedKVCache(_paged_template(), page_size=0)
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedKVCache(_paged_template(), kv_dtype="int4")
    with pytest.raises(ValueError, match="validity"):
        PagedKVCache({"k": jax.ShapeDtypeStruct((3, 16), jnp.float32)})


def test_cache_bytes_accounting_matches_buffers():
    """The regression the ISSUE names: ``nbytes`` (the serve_cache_bytes
    gauge's source) must equal the ACTUAL buffer bytes — quantized
    pools report int8 + scale bytes, not the dense dtype, and the
    host-side page-table/start/len addressing is counted."""
    template = _paged_template()
    dense = SlotCache(template)
    assert dense.nbytes == sum(
        leaf.nbytes for leaf in jax.tree.leaves(dense.cache)
    )
    f32 = PagedKVCache(template, page_size=8)
    q8 = PagedKVCache(template, page_size=8, kv_dtype="int8")
    for paged in (f32, q8):
        device = sum(
            leaf.nbytes for leaf in jax.tree.leaves(paged.cache)
        )
        host = (
            paged.page_table.nbytes + paged.start.nbytes
            + paged.lens.nbytes
        )
        assert paged.nbytes == device + host
    # int8 pools really store int8 values (+f32 scales): the dense-
    # dtype assumption would report 4x these bytes.
    assert q8.cache["layer"]["pages_k"].dtype == jnp.int8
    assert q8.cache["layer"]["scale_k"].dtype == jnp.float32
    value_bytes = q8.cache["layer"]["pages_k"].nbytes
    assert value_bytes * 4 == f32.cache["layer"]["pages_k"].nbytes
    assert q8.nbytes < f32.nbytes


def test_paged_rollover_free_long_generation():
    """The workload that forces the dense cache to roll over (see
    test_horizon_rollover_preserves_parity: 5 x 20-token requests
    through 2 slots of a 32-position model — cumulative decode writes
    cross the shared horizon several times) runs rollover-FREE on the
    paged cache, with identical tokens: slots recycle piecewise, no
    shared write index exists."""
    model = LlamaForCausalLM(LLAMA_TINY(dtype=jnp.float32, max_seq_len=32))
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2, paged=True,
    )
    rng = np.random.default_rng(5)
    requests = [
        Request(f"r{i}", rng.integers(1, 500, size=5).tolist(),
                max_new_tokens=20)
        for i in range(5)
    ]
    total_decode_tokens = sum(r.max_new_tokens for r in requests)
    assert total_decode_tokens > 32  # crosses what was the horizon
    results = session.serve(requests)
    assert session.engine.num_rollovers == 0
    assert session.engine.cache.free_pages == session.engine.cache.num_pages - 1
    for req in requests:
        want = np.asarray(
            generate(model, params, jnp.asarray(req.input_ids)[None, :],
                     max_new_tokens=20)
        )[0]
        np.testing.assert_array_equal(
            np.asarray(results[req.request_id].tokens), want
        )


def test_int8_kv_decode_parity_at_tolerance(model_and_params):
    """int8 paged KV vs the f32 path: greedy decode matches generate()
    except at genuine near-ties (reference top-2 logit margin within
    atol — the quantization contract assert_serving_parity's tolerance
    mode checks); the cache_bytes gauge reports the QUANTIZED bytes."""
    from tpudl.obs import registry

    model, params = model_and_params
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=SLOTS,
        paged=True, kv_dtype="int8",
    )
    assert session.engine.cache.quantized
    assert (
        registry().gauge("serve_cache_bytes").value
        == session.engine.cache.nbytes
    )
    assert_serving_parity(
        session, model, params, _ragged_requests(8, seed=1), atol=0.05
    )
    assert session.engine.num_rollovers == 0


def test_streaming_matches_collect(model_and_params):
    """session.stream() delivers every request's tokens incrementally;
    the concatenated chunks AND the final Result are byte-identical to
    a submit/collect run of the same requests (streaming changes
    delivery, not generation)."""
    model, params = model_and_params
    requests = _ragged_requests(6, seed=11)
    ref = _session(model, params).serve(
        [Request(**r.__dict__) for r in requests]
    )
    session = _session(model, params)
    chunks, finals, order = {}, {}, {}
    for chunk in session.stream([Request(**r.__dict__) for r in requests]):
        chunks.setdefault(chunk.request_id, []).extend(chunk.tokens)
        order.setdefault(chunk.request_id, 0)
        order[chunk.request_id] += 1
        if chunk.done:
            finals[chunk.request_id] = chunk.result
    assert set(finals) == set(ref)
    for rid in ref:
        assert chunks[rid] == finals[rid].tokens == ref[rid].tokens, rid
        assert finals[rid].finish_reason == ref[rid].finish_reason
        # Tokens arrived incrementally, not one collect-at-eos blob.
        assert order[rid] >= 2 or len(ref[rid].tokens) <= 1
    assert session.engine.on_token is None  # feed uninstalled
    with pytest.raises(ValueError, match="chunk_tokens"):
        next(session.stream([], chunk_tokens=0))


def test_stream_validates_and_submits_at_call_time(model_and_params):
    """stream() does its validation, its submission, and its claim on
    the engine's token feed AT CALL TIME: misuse raises at the call
    site (not at a far-away first iteration), a second concurrent
    stream is rejected up front, and requests handed to a stream the
    caller never iterates are still admitted — collect() finishes
    them."""
    model, params = model_and_params
    session = _session(model, params)
    with pytest.raises(ValueError, match="chunk_tokens"):
        session.stream([], chunk_tokens=0)  # no next() needed
    req = _ragged_requests(1, seed=13)[0]
    gen = session.stream([req])  # never iterated
    assert session.engine.on_token is not None  # feed claimed eagerly
    with pytest.raises(RuntimeError, match="already active"):
        session.stream([])
    results = session.collect()  # the un-iterated stream's request ran
    assert results[req.request_id].finish_reason == "length"
    assert len(results[req.request_id].tokens) == req.max_new_tokens
    with pytest.raises(StopIteration):
        next(gen)  # nothing pending: exhausts and releases the feed
    assert session.engine.on_token is None
    # A failing submit releases the feed too (no stuck claim).
    with pytest.raises(ValueError, match="duplicate"):
        session.stream([Request(**req.__dict__)] * 2)
    assert session.engine.on_token is None


def test_stream_abandoned_and_stale_feed_reclaim(model_and_params):
    """Two feed-ownership regressions: a stream() whose generator was
    dropped (GC'd) before its first iteration must not wedge the
    session — the next stream() reclaims the token feed and delivers
    the abandoned stream's admitted work too — and a STARTED generator
    that lost the feed (collect() released it, a new stream claimed it)
    stops silently instead of stepping the engine under the new
    owner."""
    import gc

    model, params = model_and_params
    session = _session(model, params)
    session.stream([Request("first", [3, 5, 7], max_new_tokens=4)])
    gc.collect()  # the un-iterated generator is gone; feed still claimed
    finals = {}
    for chunk in session.stream([Request("second", [4, 6], max_new_tokens=3)]):
        if chunk.done:
            finals[chunk.request_id] = chunk.result
    assert set(finals) == {"first", "second"}  # reclaimed, not "active"
    assert len(finals["first"].tokens) == 4

    gen3 = session.stream([Request("third", [2, 4], max_new_tokens=6)])
    assert not next(gen3).done  # started and suspended mid-feed
    session.collect()  # finishes "third", releases gen3's feed
    gen4 = session.stream([Request("fourth", [9, 1], max_new_tokens=2)])
    assert list(gen3) == []  # stale: yields nothing, steps nothing
    assert session.engine.on_token is not None  # gen4 kept its claim
    finals4 = [c.result for c in gen4 if c.done]
    assert [r.request_id for r in finals4] == ["fourth"]
    assert len(finals4[0].tokens) == 2

    # close()d before first iteration: the generator finishes without
    # ever entering its try, so its finally never releases the feed —
    # the next stream() must reclaim it (the alive-but-closed branch,
    # distinct from the GC'd one above).
    gen5 = session.stream([Request("fifth", [1, 2], max_new_tokens=2)])
    gen5.close()
    finals5 = [c.result for c in session.stream([]) if c.done]
    assert [r.request_id for r in finals5] == ["fifth"]


def test_paged_page_size_not_dividing_model_bound(model_and_params):
    """A page_size that does not divide the model's compiled bound:
    the logical per-slot bound clamps to model_seq_len (admission must
    not promise positions the decode program cannot address), and a
    prompt span that rounds past the dense prefill row zero-pads its
    last page instead of raising at trace time — which previously
    struck AFTER pages were reserved, stranding the slot."""
    model, params = model_and_params
    session = _session(model, params, paged=True, page_size=100)
    engine = session.engine
    assert engine.cache.max_seq_len == CFG.max_seq_len  # clamped, not 100
    assert engine.max_seq_len == CFG.max_seq_len
    reqs = _ragged_requests(3, seed=17)
    results = session.serve(reqs)
    for req in reqs:
        want = np.asarray(
            generate(model, params, jnp.asarray(req.input_ids)[None, :],
                     max_new_tokens=req.max_new_tokens)
        )[0]
        got = np.asarray(results[req.request_id].tokens)
        np.testing.assert_array_equal(
            got, want[: got.shape[0]],
            err_msg=f"{req.request_id} diverged on the padded-page cache",
        )


def test_never_fitting_prefill_inbox_head_sheds(model_and_params):
    """A prefilled item whose worst case exceeds what even an EMPTY
    cache could seat must shed (``shed_capacity``) instead of
    permanently blocking every prefilled request behind it — the
    disaggregation inbox is a plain deque with no deadline or
    fit-filtered-pop path, unlike AdmissionQueue."""
    import time

    from tpudl.serve.engine import _Prefilled, first_token
    from tpudl.serve.queue import _Entry

    model, params = model_and_params
    session = _session(model, params)
    engine = session.engine

    def prefilled(req):
        ids = np.asarray(req.input_ids, np.int32)
        pad = PROMPT_LEN - ids.shape[0]
        padded = np.concatenate([np.zeros(pad, np.int32), ids])[None, :]
        mask = np.concatenate(
            [np.zeros(pad, np.int32), np.ones(ids.shape[0], np.int32)]
        )[None, :]
        logits, row_cache = engine.prefill_call(engine.params, padded, mask)
        t = time.monotonic()
        return _Prefilled(
            _Entry(priority=0, seq=0, request=req, deadline=None,
                   submitted_at=t),
            row_cache, first_token(logits, req), int(ids.shape[0]), t, t,
        )

    huge = Request("huge", [1, 2, 3], max_new_tokens=CFG.max_seq_len)
    assert PROMPT_LEN + huge.max_new_tokens > CFG.max_seq_len
    ok = Request("ok", [4, 5], max_new_tokens=3)
    engine.prefill_inbox.append(prefilled(huge))
    engine.prefill_inbox.append(prefilled(ok))
    engine.run_until_drained()
    assert engine.results["huge"].finish_reason == "shed_capacity"
    assert engine.results["huge"].tokens == []
    assert engine.results["ok"].finish_reason == "length"
    assert len(engine.results["ok"].tokens) == 3
    assert not engine.prefill_inbox


def test_parity_tolerance_fires_on_wide_margin(model_and_params):
    """assert_serving_parity's atol (quantized-contract) mode measures
    the teacher-forced logit margin between the reference's choice and
    the token the engine ACTUALLY produced: a wide-margin divergence is
    a cache bug and must fire, tolerance or no tolerance."""
    import dataclasses

    model, params = model_and_params
    req = Request("t", [3, 5, 7, 11], max_new_tokens=4)
    real = _session(model, params).serve([Request(**req.__dict__)])
    logits = model.apply(
        {"params": params}, jnp.asarray(req.input_ids, jnp.int32)[None, :]
    )
    wrong = int(np.argmin(np.asarray(logits[0, -1])))
    assert wrong != real["t"].tokens[0]
    tampered = {
        "t": dataclasses.replace(
            real["t"], tokens=[wrong] + list(real["t"].tokens[1:])
        )
    }

    class _TamperedSession:
        def serve(self, requests):
            return tampered

    with pytest.raises(AssertionError, match="cache bug"):
        assert_serving_parity(
            _TamperedSession(), model, params, [req], atol=0.05
        )


def test_admission_queue_lazy_indexes_stay_bounded():
    """Lazy deletion must not leak: entries consumed through one index
    are eventually purged from the others — including the FIFO when
    promotion is disabled (it used to grow one dead entry per push for
    the process lifetime) and when a stuck live head blocks the
    head-cleanup path (compaction handles the dead middle)."""
    t = [0.0]
    q = AdmissionQueue(capacity=4, clock=lambda: t[0],
                       promote_after_s=None)
    for i in range(500):
        assert q.push(i, deadline_s=5.0)
        entry, shed = q.pop()
        assert entry.request == i and not shed
    assert len(q) == 0
    assert len(q._fifo) <= 16
    assert len(q._heap) <= 16
    assert len(q._by_deadline) <= 16

    # A live low-priority head parks in the FIFO while 500 higher-
    # priority entries churn through: the dead middle compacts.
    q2 = AdmissionQueue(capacity=4, clock=lambda: t[0],
                        promote_after_s=None)
    assert q2.push("stuck", priority=9)
    for i in range(500):
        assert q2.push(i, priority=0)
        entry, _ = q2.pop()
        assert entry.request == i
    assert len(q2) == 1  # "stuck" still waiting (promotion disabled)
    assert len(q2._fifo) <= 16
    assert len(q2._heap) <= 16
    entry, _ = q2.pop()
    assert entry.request == "stuck"


# ---------------------------------------------------------------------------
# Load-generator-driven tests (slow tier: wall-clock assertions).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_load_continuous_beats_static_wall_clock():
    """The acceptance criterion as measured: >= 1.3x tokens/sec over
    run-to-completion static batching at equal slot count on the ragged
    mix (warmed-up sessions — compilation is excluded, like every tpudl
    latency window)."""
    from benchmarks.serve_load import compare_continuous_vs_static

    cmp = compare_continuous_vs_static(n_requests=16, num_slots=4)
    assert cmp["speedup_steps"] >= 1.3, cmp
    assert cmp["speedup_tokens_per_sec"] >= 1.3, cmp
    assert cmp["continuous"]["completed"] == 16


@pytest.mark.slow
def test_serve_load_open_loop_sheds_under_overload():
    """Open loop at an absurd offered rate with tight deadlines: the
    engine keeps serving what it can and sheds the rest — overload is
    telemetry, not a crash."""
    from benchmarks.serve_load import (
        build_session,
        make_requests,
        run_open_loop,
    )

    session, _, _ = build_session(num_slots=2)
    stats = run_open_loop(
        session,
        make_requests(24, seed=1, deadline_s=0.02),
        offered_rate=5000.0,
    )
    assert stats["completed"] + stats["shed"] == 24
    assert stats["shed"] > 0
    assert stats["tokens_per_sec"] > 0


@pytest.mark.slow
def test_serve_load_replica_scaling_and_slo_overload():
    """The router acceptance criteria as measured: >= 1.7x tokens/sec
    at 2 replicas on the ragged mix (run_replica_sweep asserts it),
    int8 paged KV >= 1.8x resident slots per byte (kv_capacity_report
    asserts it), and under open-loop overload the router sheds via SLO
    burn — zero capacity sheds — with admitted p99 TTFT inside the
    objective (run_router_overload asserts all three)."""
    from benchmarks.serve_load import (
        kv_capacity_report,
        run_replica_sweep,
        run_router_overload,
    )

    cap = kv_capacity_report()
    assert cap["int8_slots_per_byte_x"] >= 1.8
    sweep = run_replica_sweep(replica_counts=(1, 2))
    two = next(s for s in sweep["sweep"] if s["replicas"] == 2)
    assert two["scaling_x"] >= 1.7
    over = run_router_overload()
    assert over["finish_reasons"].get("shed_slo", 0) > 0
    assert over["finish_reasons"].get("shed_capacity", 0) == 0
    assert over["ttft"]["p99_ms"] <= over["ttft_objective_ms"]
