"""Continuous-batching serving engine (tpudl.serve).

The correctness bar mirrors test_generate's: every request served
through the slot engine — whatever its neighbors, seat time, refills,
or horizon rollovers — must produce token-for-token what ``generate()``
produces for that request alone, through both the live model and the
deserialized StableHLO artifact pair. On top of that: admission
rejects the unservable, deadlines shed the late, and continuous
batching measurably beats run-to-completion static batching on ragged
workloads (asserted on the DETERMINISTIC decode-step count here;
benchmarks/serve_load.py carries the wall-clock claim in the slow
tier).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.serve import (
    AdmissionQueue,
    Request,
    ServeSession,
    SlotCache,
    assert_serving_parity,
)

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8
SLOTS = 4


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def _session(model, params, **kw):
    kw.setdefault("prompt_len", PROMPT_LEN)
    kw.setdefault("num_slots", SLOTS)
    return ServeSession.from_model(model, params, **kw)


def _ragged_requests(n, seed=0, max_new_lo=4, max_new_hi=20, **kw):
    rng = np.random.default_rng(seed)
    return [
        Request(
            request_id=f"r{i}",
            input_ids=rng.integers(
                1, CFG.vocab_size, size=int(rng.integers(2, PROMPT_LEN + 1))
            ).tolist(),
            max_new_tokens=int(rng.integers(max_new_lo, max_new_hi)),
            **kw,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# Tier-1 smoke: the satellite-specified config (tiny Llama, 4 slots,
# 8 requests) through the whole stack.
# ---------------------------------------------------------------------------


def test_smoke_continuous_serving(model_and_params):
    model, params = model_and_params
    session = _session(model, params)
    requests = _ragged_requests(8, seed=1)
    assert_serving_parity(session, model, params, requests)
    assert session.engine.num_prefills == 8  # every request was seated
    assert session.engine.num_decode_steps > 0


def test_results_carry_timing_and_reasons(model_and_params):
    model, params = model_and_params
    session = _session(model, params)
    results = session.serve(_ragged_requests(6, seed=2))
    assert len(results) == 6
    for res in results.values():
        assert res.finish_reason == "length"  # no eos configured
        assert res.ttft_s is not None and res.ttft_s >= 0
        # Queue wait ends at seating; TTFT adds the prefill on top.
        assert res.queue_wait_s is not None
        assert res.queue_wait_s <= res.ttft_s
        assert len(res.tokens) > 1 and res.tpot_s is not None


# ---------------------------------------------------------------------------
# Edge cases the ISSUE names.
# ---------------------------------------------------------------------------


def test_refill_on_exact_step_neighbor_emits_eos(model_and_params):
    """The moment slot A emits EOS, the waiting request is seated into
    it — while slot B keeps decoding mid-stream. Neither B nor the
    newcomer may be perturbed (bit-exact vs. each alone)."""
    model, params = model_and_params
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(1, CFG.vocab_size, size=5).tolist() for _ in range(3)
    ]
    # Probe greedily to find an eos that request A emits mid-stream.
    probe = generate(
        model, params, jnp.asarray(prompts[0])[None, :], max_new_tokens=20
    )
    eos = int(probe[0, 4])  # A finishes the step it produces token 5
    requests = [
        Request("A", prompts[0], max_new_tokens=20, eos_id=eos),
        Request("B", prompts[1], max_new_tokens=24),
        Request("C", prompts[2], max_new_tokens=8),  # seated on A's eos
    ]
    session = _session(model, params, num_slots=2)
    results = session.serve(requests)
    assert results["A"].finish_reason == "eos"
    assert results["A"].tokens[-1] == eos and len(results["A"].tokens) <= 20
    # C was refilled mid-stream: the engine never drained between A and
    # C (a drain would show as a rollover or an idle gap; prefills == 3
    # with decode steps bounded by B's runtime shows overlap).
    assert session.engine.num_prefills == 3
    assert session.engine.num_decode_steps < (20 + 24 + 8 - 3)
    for req in requests:
        want = np.asarray(
            generate(
                model, params, jnp.asarray(req.input_ids)[None, :],
                max_new_tokens=req.max_new_tokens, eos_id=req.eos_id,
            )
        )[0]
        got = np.asarray(results[req.request_id].tokens)
        np.testing.assert_array_equal(
            got, want[: got.shape[0]], err_msg=req.request_id
        )


def test_queue_timeout_shedding(model_and_params):
    """A request whose deadline passes before it is seated is shed with
    finish_reason=shed_timeout; running requests are never aborted."""
    model, params = model_and_params
    t = [0.0]
    session = _session(model, params, num_slots=2, clock=lambda: t[0])
    session.submit(Request("late", [1, 2, 3], max_new_tokens=4,
                           deadline_s=1.0))
    t[0] = 5.0  # deadline passed while queued
    session.submit(Request("ok", [1, 2, 3], max_new_tokens=4))
    results = session.collect()
    assert results["late"].finish_reason == "shed_timeout"
    assert results["late"].tokens == []
    assert results["ok"].finish_reason == "length"


def test_admission_rejects(model_and_params):
    model, params = model_and_params
    session = _session(model, params, num_slots=2)
    with pytest.raises(ValueError, match="prompt window"):
        session.submit(
            Request("long", list(range(1, PROMPT_LEN + 2)), max_new_tokens=2)
        )
    with pytest.raises(ValueError, match="max_seq_len"):
        session.submit(
            Request("huge", [1, 2], max_new_tokens=CFG.max_seq_len)
        )
    with pytest.raises(ValueError, match="at least one token"):
        session.submit(Request("empty", [], max_new_tokens=2))
    with pytest.raises(ValueError, match="max_new_tokens"):
        session.submit(Request("zero", [1], max_new_tokens=0))
    with pytest.raises(ValueError, match="uint32"):
        # Seeds ride as uint32 in the engine; out-of-range must fail at
        # admission, not mid-serving (which would strand the batch).
        session.submit(Request("neg", [1], max_new_tokens=2, seed=-1))
    session.submit(Request("dup", [1, 2], max_new_tokens=2))
    with pytest.raises(ValueError, match="duplicate"):
        session.submit(Request("dup", [1, 2], max_new_tokens=2))
    results = session.collect()
    assert results["dup"].ok


def test_queue_capacity_sheds(model_and_params):
    model, params = model_and_params
    session = _session(model, params, num_slots=2, queue_capacity=2)
    for i in range(4):
        session.submit(Request(f"q{i}", [1, 2], max_new_tokens=3))
    results = session.collect()
    reasons = sorted(r.finish_reason for r in results.values())
    assert reasons == ["length", "length", "shed_capacity", "shed_capacity"]


def test_artifact_vs_live_parity(model_and_params, tmp_path):
    """A ServeSession fed the StableHLO artifact pair produces
    token-for-token the same outputs as the live model — and as
    generate() — for the same seeds, through files on disk."""
    from tpudl.export.decode import export_serving_decoder

    model, params = model_and_params
    prefix = str(tmp_path / "serve_tiny")
    export_serving_decoder(
        model, params, num_slots=SLOTS, prompt_len=PROMPT_LEN,
        path_prefix=prefix,
    )
    art = ServeSession.from_artifacts(
        f"{prefix}.prefill.stablehlo", f"{prefix}.decode.stablehlo", params
    )
    assert (art.num_slots, art.prompt_len, art.max_seq_len) == (
        SLOTS, PROMPT_LEN, CFG.max_seq_len,
    )
    # Mixed greedy + sampled workload, same seeds through both backends.
    requests = _ragged_requests(8, seed=4)
    for i, req in enumerate(requests):
        if i % 3 == 0:
            req.temperature = 0.8
            req.seed = 100 + i
    live = _session(model, params)
    r_live = live.serve([Request(**r.__dict__) for r in requests])
    r_art = art.serve([Request(**r.__dict__) for r in requests])
    for rid in r_live:
        assert r_live[rid].tokens == r_art[rid].tokens, rid
    # Greedy requests additionally match live generate() run alone.
    for req in requests:
        if req.temperature:
            continue
        want = np.asarray(
            generate(
                model, params, jnp.asarray(req.input_ids)[None, :],
                max_new_tokens=req.max_new_tokens,
            )
        )[0]
        np.testing.assert_array_equal(
            np.asarray(r_live[req.request_id].tokens),
            want[: len(r_live[req.request_id].tokens)],
        )


def test_horizon_rollover_preserves_parity(model_and_params):
    """More queued decode work than one cache horizon holds: the engine
    rolls the cache over between waves and every request still matches
    its solo generation."""
    model = LlamaForCausalLM(LLAMA_TINY(dtype=jnp.float32, max_seq_len=32))
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2
    )
    rng = np.random.default_rng(5)
    requests = [
        Request(f"r{i}", rng.integers(1, 500, size=5).tolist(),
                max_new_tokens=20)
        for i in range(5)
    ]
    results = session.serve(requests)
    assert session.engine.num_rollovers >= 1
    # The host-mirrored write index stayed in lockstep with the
    # device-side scalar through seats, decode steps, and resets.
    device_index = next(
        int(leaf)
        for leaf in jax.tree.leaves(session.engine.cache.cache)
        if leaf.ndim == 0
    )
    assert device_index == session.engine.cache.write_index
    for req in requests:
        want = np.asarray(
            generate(model, params, jnp.asarray(req.input_ids)[None, :],
                     max_new_tokens=20)
        )[0]
        np.testing.assert_array_equal(
            np.asarray(results[req.request_id].tokens), want
        )


def test_sampling_is_batch_composition_independent(model_and_params):
    """Token t of a sampled request draws from fold_in(key(seed), t):
    the same request yields the same tokens served alone or in a full
    ragged batch — reproducibility generate()'s shared rng stream
    cannot offer."""
    model, params = model_and_params
    req = Request("s", [7, 8, 9], max_new_tokens=10, temperature=1.0, seed=42)
    alone = _session(model, params).serve([Request(**req.__dict__)])
    crowd_reqs = [Request(**req.__dict__)] + _ragged_requests(6, seed=6)
    crowd = _session(model, params).serve(crowd_reqs)
    assert alone["s"].tokens == crowd["s"].tokens
    # And a different seed actually changes the stream.
    other = Request("s", [7, 8, 9], max_new_tokens=10, temperature=1.0,
                    seed=43)
    r_other = _session(model, params).serve([other])
    assert r_other["s"].tokens != alone["s"].tokens


def test_continuous_beats_static_on_decode_steps(model_and_params):
    """The acceptance ratio on its deterministic basis: equal slots,
    ragged lengths, the SAME engine with mid-stream refill on vs off —
    continuous must finish the workload in >= 1.3x fewer decode steps
    (wall-clock tokens/sec rides this 1:1 at fixed slot count; the slow
    tier asserts the timed version via benchmarks/serve_load.py)."""
    model, params = model_and_params
    lengths = [40, 6, 6, 6, 40, 6, 6, 6]
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 500, size=5).tolist() for _ in lengths]

    def reqs():
        return [
            Request(f"r{i}", prompts[i], max_new_tokens=n)
            for i, n in enumerate(lengths)
        ]

    cont = _session(model, params)
    r_cont = cont.serve(reqs())
    stat = _session(model, params, continuous=False)
    r_stat = stat.serve(reqs())
    assert all(r.ok for r in r_cont.values())
    # Identical tokens either way — batching policy is invisible to
    # outputs, it only moves time.
    for rid in r_cont:
        assert r_cont[rid].tokens == r_stat[rid].tokens, rid
    ratio = stat.engine.num_decode_steps / cont.engine.num_decode_steps
    assert ratio >= 1.3, (
        f"continuous batching only {ratio:.2f}x fewer decode steps than "
        f"static (cont={cont.engine.num_decode_steps}, "
        f"stat={stat.engine.num_decode_steps})"
    )


def test_serve_obs_flow(model_and_params):
    """Engine metrics land in the obs registry: busy gauge, TTFT/TPOT
    histograms, completion counters, cache byte accounting."""
    from tpudl.obs import registry

    model, params = model_and_params
    reg = registry()
    completed0 = reg.counter("serve_requests_completed").value
    prefills0 = reg.counter("serve_prefills").value
    ttft0 = reg.histogram("serve_ttft_ms").count
    session = _session(model, params, num_slots=2)
    session.serve(_ragged_requests(4, seed=8))
    assert reg.counter("serve_requests_completed").value == completed0 + 4
    assert reg.counter("serve_prefills").value == prefills0 + 4
    assert reg.histogram("serve_ttft_ms").count == ttft0 + 4
    assert reg.gauge("serve_slots_busy").value == 0  # drained
    assert reg.gauge("serve_cache_bytes").value > 0


# ---------------------------------------------------------------------------
# Queue and cache units (host-only, no model).
# ---------------------------------------------------------------------------


def test_admission_queue_priority_fifo_and_fit():
    t = [0.0]
    q = AdmissionQueue(capacity=8, clock=lambda: t[0])

    class R:
        def __init__(self, name, size=1):
            self.name, self.size = name, size

    assert q.push(R("b0"), priority=1)
    assert q.push(R("a0"), priority=0)
    assert q.push(R("a1"), priority=0)
    assert q.push(R("big", size=99), priority=0)
    # Priority first, FIFO within priority, fit-filter skips without
    # reordering what it skips.
    entry, shed = q.pop(fit=lambda r: r.size < 10)
    assert entry.request.name == "a0" and not shed
    entry, _ = q.pop(fit=lambda r: r.size < 10)
    assert entry.request.name == "a1"
    entry, _ = q.pop(fit=lambda r: r.size < 10)
    assert entry.request.name == "b0"  # "big" skipped, still queued
    assert len(q) == 1
    entry, _ = q.pop()
    assert entry.request.name == "big"


def test_admission_queue_deadlines_and_capacity():
    t = [0.0]
    q = AdmissionQueue(capacity=2, clock=lambda: t[0])
    assert q.push("x", deadline_s=1.0)
    assert q.push("y")
    assert not q.push("overflow")  # bounded
    t[0] = 2.0
    entry, shed = q.pop()
    assert entry.request == "y"  # x expired on the way
    assert [e.request for e in shed] == ["x"]
    q.push("z", deadline_s=0.5)
    t[0] = 9.0
    assert [e.request for e in q.drain_expired()] == ["z"]
    assert len(q) == 0
    with pytest.raises(ValueError, match="capacity"):
        AdmissionQueue(capacity=0)


def test_slot_cache_bookkeeping():
    template = {
        "layer": {
            "k": jax.ShapeDtypeStruct((3, 16, 2, 4), jnp.float32),
            "valid": jax.ShapeDtypeStruct((3, 16), jnp.bool_),
            "index": jax.ShapeDtypeStruct((), jnp.int32),
        }
    }
    cache = SlotCache(template)
    assert (cache.num_slots, cache.max_seq_len) == (3, 16)
    assert cache.write_index == 0 and cache.remaining_horizon == 16
    row = {
        "layer": {
            "k": jnp.ones((1, 16, 2, 4), jnp.float32),
            "valid": jnp.asarray([[True] * 5 + [False] * 11]),
            "index": jnp.int32(5),
        }
    }
    cache.insert(row, 1)
    assert cache.write_index == 0  # row's own index never leaks in
    np.testing.assert_array_equal(cache.valid_counts(), [0, 5, 0])
    cache.set_write_index(5)
    assert cache.write_index == 5 and cache.remaining_horizon == 11
    cache.free(1)
    np.testing.assert_array_equal(cache.valid_counts(), [0, 0, 0])
    assert cache.write_index == 5  # free touches validity only
    cache.advance_write_index()  # host mirror of one decode dispatch
    assert cache.write_index == 6 and cache.remaining_horizon == 10
    cache.reset()
    assert cache.write_index == 0
    assert cache.nbytes > 0
    with pytest.raises(IndexError):
        cache.insert(row, 3)
    with pytest.raises(ValueError, match="validity"):
        SlotCache({"k": jax.ShapeDtypeStruct((3, 16), jnp.float32)})


# ---------------------------------------------------------------------------
# Load-generator-driven tests (slow tier: wall-clock assertions).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_load_continuous_beats_static_wall_clock():
    """The acceptance criterion as measured: >= 1.3x tokens/sec over
    run-to-completion static batching at equal slot count on the ragged
    mix (warmed-up sessions — compilation is excluded, like every tpudl
    latency window)."""
    from benchmarks.serve_load import compare_continuous_vs_static

    cmp = compare_continuous_vs_static(n_requests=16, num_slots=4)
    assert cmp["speedup_steps"] >= 1.3, cmp
    assert cmp["speedup_tokens_per_sec"] >= 1.3, cmp
    assert cmp["continuous"]["completed"] == 16


@pytest.mark.slow
def test_serve_load_open_loop_sheds_under_overload():
    """Open loop at an absurd offered rate with tight deadlines: the
    engine keeps serving what it can and sheds the rest — overload is
    telemetry, not a crash."""
    from benchmarks.serve_load import (
        build_session,
        make_requests,
        run_open_loop,
    )

    session, _, _ = build_session(num_slots=2)
    stats = run_open_loop(
        session,
        make_requests(24, seed=1, deadline_s=0.02),
        offered_rate=5000.0,
    )
    assert stats["completed"] + stats["shed"] == 24
    assert stats["shed"] > 0
    assert stats["tokens_per_sec"] > 0
