"""Train-state checkpoint/resume (SURVEY.md §5.4): the recovery story the
reference lacks (it writes three serialization formats, reads none back —
reference notebooks/cv/onnx_experiments.py:33-42,198,212-215)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.checkpoint import (
    CheckpointManager,
    restore_train_state,
    save_train_state,
)
from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models import ResNet18
from tpudl.parallel.sharding import FSDP_RULES
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    make_classification_train_step,
)


def _fresh_state(seed=0):
    model = ResNet18(num_classes=10, small_inputs=True)
    return create_train_state(
        jax.random.key(seed),
        model,
        jnp.zeros((1, 16, 16, 3)),
        optax.adamw(1e-3),
    )


def _batches(n):
    return list(
        synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=10, num_batches=n
        )
    )


def test_save_restore_roundtrip(tmp_path):
    state = _fresh_state()
    path = str(tmp_path / "ckpt")
    save_train_state(path, state)
    restored = restore_train_state(path, _fresh_state(seed=1))
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(restored.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(state.step)
    # Optimizer state (adamw mu/nu) round-trips too.
    for a, b in zip(
        jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_matches_uninterrupted_run(tmp_path):
    """train 5 -> save -> train 5 more == train 10 straight (exact, CPU)."""
    mesh = make_mesh(MeshSpec(dp=-1))
    step_fn = make_classification_train_step()
    rng = jax.random.key(42)
    batches = _batches(10)

    # Uninterrupted run.
    state_a = _fresh_state()
    step_a = compile_step(step_fn, mesh, state_a, None, donate_state=False)
    losses_a = []
    for b in batches:
        state_a, m = step_a(state_a, b, rng)
        losses_a.append(float(m["loss"]))

    # Interrupted at step 5.
    state_b = _fresh_state()
    step_b = compile_step(step_fn, mesh, state_b, None, donate_state=False)
    for b in batches[:5]:
        state_b, _ = step_b(state_b, b, rng)
    path = str(tmp_path / "ckpt")
    save_train_state(path, state_b)

    # "New process": fresh init, restore, continue on batches[5:].
    state_c = restore_train_state(path, _fresh_state(seed=9))
    assert int(state_c.step) == 5
    step_c = compile_step(step_fn, mesh, state_c, None, donate_state=False)
    losses_c = []
    for b in batches[5:]:
        state_c, m = step_c(state_c, b, rng)
        losses_c.append(float(m["loss"]))

    np.testing.assert_allclose(losses_c, losses_a[5:], rtol=1e-6, atol=1e-7)


def test_sharded_restore_onto_mesh(mesh8, tmp_path):
    """Restore places leaves per FSDP rules on the 8-device mesh: the
    resume-on-a-topology path for big models."""
    state = _fresh_state()
    path = str(tmp_path / "ckpt")
    save_train_state(path, state)

    restored = restore_train_state(
        path, _fresh_state(seed=2), mesh=mesh8, rules=FSDP_RULES
    )
    # The largest conv kernel must actually land fsdp-sharded.
    leaves = jax.tree_util.tree_leaves_with_path(restored.params)
    sharded = [
        (jax.tree_util.keystr(p), l) for p, l in leaves
        if hasattr(l, "sharding") and not l.sharding.is_fully_replicated
    ]
    assert sharded, "no parameter came back sharded under FSDP rules"
    for _, leaf in sharded:
        assert "fsdp" in str(leaf.sharding.spec)


def test_checkpoint_manager_retention_and_latest(tmp_path):
    state = _fresh_state()
    with CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2) as mgr:
        for s in (1, 2, 3):
            state = state.replace(step=jnp.asarray(s, jnp.int32))
            assert mgr.save(s, state)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 3
        assert list(mgr.all_steps()) == [2, 3]
        restored = mgr.restore(_fresh_state(seed=3))
        assert int(restored.step) == 3


def test_manager_restore_without_checkpoint_raises(tmp_path):
    with CheckpointManager(str(tmp_path / "empty")) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore(_fresh_state())


def test_fit_periodic_checkpoint_and_resume_latest(tmp_path):
    """fit(checkpoint_manager=...) saves every N steps + at the end, and
    resume_latest restores the newest into a fresh state (the one-call
    cold-start-or-resume site)."""
    from tpudl.train import fit, resume_latest

    mesh = make_mesh(MeshSpec(dp=-1))
    step_fn = make_classification_train_step()
    rng = jax.random.key(0)

    state = _fresh_state()
    step = compile_step(step_fn, mesh, state, None, donate_state=False)
    with CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=5) as mgr:
        state, start = resume_latest(mgr, state)
        assert start == 0  # cold start: nothing to restore
        state, _, _ = fit(
            step,
            state,
            _batches(7),
            rng,
            checkpoint_manager=mgr,
            checkpoint_every=3,
        )
        # Saved at steps 3, 6 (periodic) and 7 (final).
        assert mgr.all_steps() == [3, 6, 7]

    # "New process": fresh manager + fresh state, resume from latest.
    with CheckpointManager(str(tmp_path / "ckpts")) as mgr2:
        resumed, start = resume_latest(mgr2, _fresh_state(seed=3))
        assert start == 7 and int(resumed.step) == 7
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(resumed.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
