"""MoE / expert parallelism (tpudl.ops.moe) on the fake 8-CPU mesh.

Parity strategy: with every expert holding identical weights and ample
capacity, routing must be numerically invisible (combine weights
renormalize to 1), so the MoE layer equals the dense FFN it replaces —
for any k, on and off the ep mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.ops.moe import (
    EP_MOE_RULES,
    MoEMlp,
    expert_capacity,
    route_topk,
    with_moe_rules,
)
from tpudl.parallel.sharding import FSDP_RULES, active_mesh, tree_shardings
from tpudl.runtime.mesh import MeshSpec, make_mesh

B, S, M, H, E = 4, 16, 8, 32, 4


def test_expert_capacity():
    assert expert_capacity(128, 8, 2, 1.25) == 40
    assert expert_capacity(4, 64, 1, 1.0) == 1


def test_route_topk_dispatches_all_with_ample_capacity():
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.key(0), (B, S, E)), -1
    )
    disp, comb, aux = route_topk(probs, k=2, capacity=S * 2)
    # Every token lands k slots.
    np.testing.assert_allclose(float(jnp.sum(disp)), B * S * 2, rtol=1e-6)
    # Combine weights renormalize to exactly 1 per token.
    np.testing.assert_allclose(
        np.asarray(jnp.sum(comb, axis=(2, 3))), 1.0, atol=1e-5
    )


def test_route_topk_capacity_drops_tokens():
    # Force every token to expert 0: only `capacity` survive.
    probs = jnp.zeros((1, S, E)).at[:, :, 0].set(1.0)
    disp, comb, _ = route_topk(probs, k=1, capacity=3)
    assert float(jnp.sum(disp)) == 3.0
    # Dropped tokens carry zero combine weight.
    per_token = jnp.sum(comb, axis=(2, 3))[0]
    np.testing.assert_allclose(np.asarray(per_token[:3]), 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(per_token[3:]), 0.0, atol=1e-6)


def test_route_topk_dropped_choice_shrinks_combine_weight():
    """GShard normalization: a capacity-dropped choice's gate mass reduces
    the surviving choices' combine weight — it is NOT renormalized onto
    the survivor (the dropped mass rides the residual connection)."""
    probs = jnp.asarray(
        [[[0.6, 0.3, 0.05, 0.05],   # token 0: top-2 = experts 0, 1
          [0.6, 0.05, 0.3, 0.05]]]  # token 1: top-2 = experts 0, 2
    )
    # capacity=1: expert 0 keeps only token 0; token 1's expert-0 mass
    # drops, its second choice (expert 2, uncontended) survives.
    _, comb, _ = route_topk(probs, k=2, capacity=1)
    per_token = jnp.sum(comb, axis=(2, 3))[0]
    np.testing.assert_allclose(float(per_token[0]), 1.0, atol=1e-6)
    np.testing.assert_allclose(
        float(per_token[1]), 0.3 / (0.6 + 0.3), atol=1e-6
    )


def test_route_topk_aux_loss_uniform_router():
    probs = jnp.full((B, S, E), 1.0 / E)
    _, _, aux = route_topk(probs, k=1, capacity=S)
    # Switch aux loss is 1.0 at perfect balance (argmax ties all resolve
    # to expert 0, but f*p summed still equals 1/E * 1 * E).
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


def _identical_expert_moe(k):
    """MoEMlp params where every expert is the same dense FFN."""
    layer = MoEMlp(
        num_experts=E,
        intermediate_size=H,
        k=k,
        capacity_factor=float(E),  # ample: C = k*S
        dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.key(1), (B, S, M))
    params = layer.init(jax.random.key(2), x)["params"]
    wi0 = params["wi"][0]
    wo0 = params["wo"][0]
    params = dict(params)
    params["wi"] = jnp.broadcast_to(wi0, params["wi"].shape)
    params["wo"] = jnp.broadcast_to(wo0, params["wo"].shape)
    return layer, params, x, wi0, wo0


@pytest.mark.parametrize("k", [1, 2])
def test_moe_identical_experts_match_dense(k):
    layer, params, x, wi0, wo0 = _identical_expert_moe(k)
    y = layer.apply({"params": params}, x)
    expected = jax.nn.gelu(x @ wi0) @ wo0
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(expected), atol=1e-4
    )


def test_moe_parity_on_ep_mesh():
    """The ep-sharded run (dispatch all-to-all compiled in) matches the
    unmeshed single-device run bit-for-bit at f32."""
    layer, params, x, _, _ = _identical_expert_moe(1)
    y_ref = layer.apply({"params": params}, x)

    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=1, ep=4))
    shardings = tree_shardings(mesh, params, with_moe_rules(FSDP_RULES))
    params_sharded = jax.device_put(params, shardings)
    with active_mesh(mesh):
        y = jax.jit(lambda p, xx: layer.apply({"params": p}, xx))(
            params_sharded, x
        )
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)


def test_moe_rules_shard_expert_dim():
    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=1, ep=4))
    layer = MoEMlp(num_experts=E, intermediate_size=H, dtype=jnp.float32)
    x = jnp.zeros((B, S, M))
    params = layer.init(jax.random.key(3), x)["params"]
    sh = tree_shardings(mesh, params, with_moe_rules(FSDP_RULES))
    assert sh["wi"].spec[0] == "ep"
    assert sh["wo"].spec[0] == "ep"
    assert sh["router"]["kernel"].spec == jax.sharding.PartitionSpec(None, None)


def test_moe_llama_trains_and_sows_aux():
    """llama-tiny-moe end-to-end: loss decreases, moe_aux metric reported,
    router gets gradients."""
    from tpudl.data.synthetic import synthetic_token_batches
    from tpudl.models.registry import build_model
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = build_model(
        "llama-tiny-moe", num_classes=2, dtype=jnp.float32, moe_experts=4
    )
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 16), jnp.int32),
        optax.adam(1e-3),
        init_kwargs={},
    )
    assert "moe" in state.params["model"]["layer_0"]

    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=1, ep=4))
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"),
            label_key="label",
            moe_aux_weight=0.01,
        ),
        mesh,
        state,
        with_moe_rules(FSDP_RULES),
    )
    it = synthetic_token_batches(16, seq_len=16, vocab_size=512)
    batch = next(it)
    rng = jax.random.key(1)
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert "moe_aux" in metrics and float(metrics["moe_aux"]) > 0.0
    assert losses[-1] < losses[0]
