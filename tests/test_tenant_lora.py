"""Multi-tenant LoRA serving: segmented kernel parity, AdapterPool
lifecycle (load / LRU evict / transparent reload / lease safety),
engine + router integration, migration re-pinning, and the composed
quantized-base + LoRA config (PR-14)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.llama import LlamaConfig, LlamaForCausalLM
from tpudl.models.lora import (
    extract_adapters,
    merge_adapter,
    strip_adapters,
)
from tpudl.obs import registry
from tpudl.serve import AdapterPool, Request, ServeSession
from tpudl.serve.lora import assert_tenant_parity

#: Deliberately tiny: every test here compiles its own lora programs
#: on CPU, so model size is test wall-time.
TINY = dict(
    vocab_size=128,
    hidden_size=32,
    num_layers=1,
    num_heads=2,
    num_kv_heads=1,
    intermediate_size=64,
    max_seq_len=64,
    rope_theta=10_000.0,
    dtype=jnp.float32,
)
PROMPT_LEN = 8


@pytest.fixture(scope="module")
def base():
    cfg = LlamaConfig(**TINY)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def make_adapter(seed: int, rank: int = 2, b_scale: float = 0.05):
    cfg = LlamaConfig(**TINY, lora_rank=rank)
    lp = LlamaForCausalLM(cfg).init(
        jax.random.key(seed), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    flat = extract_adapters(lp)
    rng = np.random.default_rng(seed)
    return {
        path: {
            "lora_a": np.asarray(f["lora_a"]),
            "lora_b": rng.normal(
                scale=b_scale, size=np.shape(f["lora_b"])
            ).astype(np.float32),
        }
        for path, f in flat.items()
    }


@pytest.fixture(scope="module")
def adapters():
    # Ragged ranks on purpose: tenant "t2" is rank 1 under r_max 2, so
    # its unused table entry exercises the zero-page contract.
    return {
        "t0": make_adapter(1),
        "t1": make_adapter(2),
        "t2": make_adapter(3, rank=1),
    }


def tenant_requests(tenants, n=6, seed=0, max_new=(4, 10)):
    rng = np.random.default_rng(seed)
    cycle = [None] + list(tenants)
    return [
        Request(
            request_id=f"r{seed}-{i}",
            input_ids=rng.integers(
                1, 100, size=int(rng.integers(2, PROMPT_LEN + 1))
            ).tolist(),
            max_new_tokens=int(rng.integers(*max_new)),
            tenant=cycle[i % len(cycle)],
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# the segmented kernel
# ---------------------------------------------------------------------------


def test_segmented_lora_fused_matches_reference():
    """Pallas (interpret) vs XLA composite on ragged tables: empty
    slots, short ranks via zero pages, f32 and int8 pools, [B, H] and
    [B, S, H] activations."""
    from tpudl.ops.segmented_lora import segmented_lora

    rng = np.random.default_rng(0)
    np_, h, o, p = 9, 24, 40, 3
    pools = {
        "a": jnp.asarray(rng.normal(size=(np_, h)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(np_, o)), jnp.float32),
    }
    # Page 0 is the all-zero page by contract.
    pools = {
        "a": pools["a"].at[0].set(0.0), "b": pools["b"].at[0].set(0.0)
    }
    table = np.array(
        [[1, 2, 3], [4, 0, 0], [0, 0, 0], [5, 6, 0]], np.int32
    )
    scale = np.array([0.5, 2.0, 0.0, 1.0], np.float32)
    x = jnp.asarray(rng.normal(size=(4, 2, h)), jnp.float32)
    ref = segmented_lora(x, pools, table, scale, impl="reference")
    fused = segmented_lora(x, pools, table, scale, impl="fused")
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(fused), rtol=2e-5, atol=2e-6
    )
    # Hand-computed row 0 (full-rank slot).
    a = np.asarray(pools["a"])[table[0]].T
    b = np.asarray(pools["b"])[table[0]]
    want = 0.5 * (np.asarray(x)[0] @ a) @ b
    np.testing.assert_allclose(np.asarray(ref)[0], want, rtol=1e-5)
    # Empty slot contributes exactly zero.
    assert np.abs(np.asarray(fused)[2]).max() == 0.0
    # int8 pools with per-page scales.
    qa = np.clip(
        np.round(np.asarray(pools["a"]) / 0.01), -127, 127
    ).astype(np.int8)
    qb = np.clip(
        np.round(np.asarray(pools["b"]) / 0.02), -127, 127
    ).astype(np.int8)
    qpools = {
        "a": jnp.asarray(qa), "b": jnp.asarray(qb),
        "a_scale": jnp.full((np_,), 0.01, jnp.float32),
        "b_scale": jnp.full((np_,), 0.02, jnp.float32),
    }
    r8 = segmented_lora(x, qpools, table, scale, impl="reference")
    f8 = segmented_lora(x, qpools, table, scale, impl="fused")
    np.testing.assert_allclose(
        np.asarray(r8), np.asarray(f8), rtol=2e-5, atol=2e-6
    )
    # 2-D activation form.
    r2 = segmented_lora(x[:, 0], pools, table, scale, impl="fused")
    np.testing.assert_allclose(
        np.asarray(r2), np.asarray(fused)[:, 0], rtol=1e-6
    )


# ---------------------------------------------------------------------------
# AdapterPool lifecycle
# ---------------------------------------------------------------------------


def test_adapter_pool_register_validates(base, adapters):
    model, _ = base
    pool = AdapterPool(model.cfg, r_max=2, num_slots=2, num_pages=9)
    with pytest.raises(ValueError, match="no lora_a"):
        pool.register("empty", {})
    bad = {
        "model/layer_0/attention/q_proj": {
            "lora_a": np.zeros((7, 2), np.float32),  # wrong in-dim
            "lora_b": np.zeros((2, 32), np.float32),
        }
    }
    with pytest.raises(ValueError, match="do not fit site"):
        pool.register("bad", bad)
    big = make_adapter(9, rank=4)
    with pytest.raises(ValueError, match="outside"):
        pool.register("big", big)  # rank 4 > r_max 2
    with pytest.raises(ValueError, match="not an adaptable site"):
        pool.register("alien", {
            "model/layer_0/lm_head": {
                "lora_a": np.zeros((32, 2), np.float32),
                "lora_b": np.zeros((2, 32), np.float32),
            }
        })


def test_adapter_pool_lru_eviction_and_lease_safety(base, adapters):
    """Satellite: refcount-0 LRU reclaim under pressure; an adapter
    leased by a seated request is NEVER evicted mid-decode."""
    model, _ = base
    # Room for exactly two rank-2 adapters (pages 1..4 + zero page).
    pool = AdapterPool(model.cfg, r_max=2, num_slots=2, num_pages=5)
    for tid, tree in adapters.items():
        pool.register(tid, tree)
    row0, _ = pool.acquire("t0")
    assert set(row0[row0 != 0]) and pool.resident_since("t0") is not None
    pool.release("t0")  # refcount 0: cached, evictable
    pool.acquire("t1")
    pool.release("t1")
    assert pool.stats()["resident"] == 2 and pool.free_pages == 0
    # Loading t2 (rank 1) under pressure evicts the LRU refcount-0
    # resident — t0, the older stamp.
    pool.acquire("t2")
    stats = pool.stats()
    assert stats["evictions"] == 1
    assert pool.resident_since("t0") is None, "LRU victim should be t0"
    assert pool.resident_since("t1") is not None
    pool.release("t2")
    # Lease safety: pin t1 and t2 (3 pages), then t0 (2 pages) cannot
    # load — only 1 page is reclaimable and NO leased adapter may be
    # touched.
    pool.acquire("t1")
    pool.acquire("t2")
    assert not pool.can_seat("t0")
    with pytest.raises(RuntimeError, match="leased"):
        pool.acquire("t0")
    assert pool.resident_since("t1") is not None
    assert pool.resident_since("t2") is not None
    pool.release("t1")
    pool.release("t2")
    # Pressure relieved: t0 reloads (its pages were reclaimed).
    pool.acquire("t0")
    assert pool.stats()["reloads"] >= 1
    pool.release("t0")


def test_adapter_pool_nbytes_reconciles_with_buffers(base, adapters):
    """Satellite (the PR-8 byte-accounting idiom): ``nbytes`` — the
    number ``serve_adapters_per_gb`` divides into — must equal the sum
    of the ACTUAL buffer nbytes (int8 values AND f32 scale rows AND
    the host slot tables), not a dtype-assuming estimate."""
    model, _ = base
    for dtype in (None, "int8"):
        pool = AdapterPool(
            model.cfg, r_max=2, num_slots=4, num_pages=9, dtype=dtype
        )
        device = sum(
            leaf.nbytes for leaf in jax.tree.leaves(pool.pools)
        )
        want = device + pool.slot_table.nbytes + pool.slot_scale.nbytes
        assert pool.nbytes == want
        assert pool.bytes_per_page * pool.num_pages == device
        if dtype == "int8":
            # Scale rows are f32 and must be inside the accounting:
            # an int8 pool without them would under-report.
            scale_bytes = sum(
                leaf.nbytes
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    pool.pools
                )[0]
                if "scale" in jax.tree_util.keystr(path)
            )
            assert scale_bytes > 0
        # Capacity arithmetic follows the same bytes.
        assert pool.adapters_per_gb(2) == 1e9 / (pool.bytes_per_page * 2)


def test_evicted_tenant_reloads_transparently(base, adapters):
    """Satellite: after eviction, the tenant's NEXT request reloads
    the adapter with no caller-visible difference — same tokens as an
    always-resident run — and serve_adapter_reloads_total counts it."""
    model, params = base
    # Pool holds ONE rank-2 adapter: t0 and t1 must thrash.
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"], "t1": adapters["t1"]},
        adapter_pages=3,
    )
    reloads0 = registry().counter("serve_adapter_reloads_total").value
    r0 = Request("a", [3, 4, 5], max_new_tokens=4, tenant="t0")
    r1 = Request("b", [3, 4, 5], max_new_tokens=4, tenant="t1")
    r2 = Request("c", [3, 4, 5], max_new_tokens=4, tenant="t0")
    out0 = session.serve([r0])  # loads t0
    out1 = session.serve([r1])  # evicts t0, loads t1
    out2 = session.serve([r2])  # transparent reload of t0
    assert out0["a"].ok and out1["b"].ok and out2["c"].ok
    assert out2["c"].tokens == out0["a"].tokens, (
        "a reloaded adapter must serve identical tokens"
    )
    pool = session.engine.adapter_pool
    assert pool.stats()["evictions"] >= 1
    assert pool.stats()["reloads"] >= 1
    assert (
        registry().counter("serve_adapter_reloads_total").value
        > reloads0
    )
    # And the reference is still the merged adapter, not the base.
    merged = merge_adapter(params, adapters["t0"])
    from tpudl.models.generate import generate

    want = np.asarray(generate(
        model, merged, jnp.asarray([[3, 4, 5]], jnp.int32),
        max_new_tokens=4,
    ))[0]
    np.testing.assert_array_equal(np.asarray(out2["c"].tokens), want)


# ---------------------------------------------------------------------------
# engine parity (the acceptance gates)
# ---------------------------------------------------------------------------


def test_multi_tenant_parity_exact_f32(base, adapters):
    """The heterogeneous batch — mixed tenants + tenantless slots,
    ragged ranks — serves EXACT tokens vs the sequential
    one-adapter-at-a-time merged reference, through BOTH kernel paths
    (Pallas interpret and XLA composite)."""
    model, params = base
    reqs = tenant_requests(adapters, n=7, seed=0)
    for impl in ("fused", "reference"):
        session = ServeSession.from_model(
            model, params, prompt_len=PROMPT_LEN, num_slots=4,
            adapters=adapters, adapter_impl=impl,
        )
        assert_tenant_parity(
            session, model, params, adapters, reqs, atol=None
        )


def test_multi_tenant_parity_int8_pages_margin(base, adapters):
    """int8 adapter pages: a greedy flip must be a genuine near-tie
    under the teacher-forced logit margin (per-tenant merged
    reference). alpha=4 keeps the page-quantization error at weight-
    cell scale — the contract the grid's lora8 cell pins."""
    model, params = base
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=4,
        adapters=adapters, adapter_dtype="int8", adapter_alpha=4.0,
    )
    assert_tenant_parity(
        session, model, params, adapters,
        tenant_requests(adapters, n=6, seed=1),
        atol=0.1, alpha=4.0,
    )


def test_quantized_base_composes_with_adapters(base, adapters):
    """The lifted mutual exclusion, serving half: int8 BASE weights +
    per-tenant f32 adapters in one session (margin parity vs the f32
    merged reference — exactly the int8-weight cell's contract, now
    with adapters on top)."""
    model, params = base
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=4,
        adapters=adapters, weight_dtype="int8",
    )
    assert_tenant_parity(
        session, model, params, adapters,
        tenant_requests(adapters, n=5, seed=2, max_new=(4, 7)),
        atol=0.1,
    )


# ---------------------------------------------------------------------------
# config composition (satellite: the lifted raise)
# ---------------------------------------------------------------------------


def test_lora_rank_weight_dtype_compose_in_config():
    """LlamaConfig(weight_dtype=..., lora_rank>0) no longer raises:
    the projection runs a LoRADense over a quantized base kernel, and
    quantize_model on a LoRA tree quantizes ONLY the base kernels."""
    from tpudl.quant import quantize_model
    from tpudl.quant.quantize import is_quantized

    cfg = LlamaConfig(**TINY, lora_rank=2)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(1), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    qmodel, qparams = quantize_model(model, params, "int8")
    assert qmodel.cfg.weight_dtype == "int8"
    assert qmodel.cfg.lora_rank == 2
    site = qparams["model"]["layer_0"]["attention"]["q_proj"]
    assert is_quantized(site["kernel"])
    assert site["lora_a"].dtype == jnp.float32  # adapters stay full
    ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    q_logits = qmodel.apply({"params": qparams}, ids)
    # Reference: dequantize the base, run the plain lora model.
    from tpudl.quant import dequantize_tree

    ref_logits = model.apply({"params": dequantize_tree(qparams)}, ids)
    np.testing.assert_allclose(
        np.asarray(q_logits), np.asarray(ref_logits),
        rtol=5e-2, atol=5e-2,
    )


def test_lora_rank_validation():
    with pytest.raises(ValueError, match="lora_rank"):
        LlamaConfig(**TINY, lora_rank=-1)


def test_adapter_helpers_roundtrip(base, adapters):
    """strip/extract/merge are consistent: stripping a LoRA tree
    yields the base structure, and merging the extracted adapter
    reproduces LoRADense's own math."""
    model, params = base
    cfg = LlamaConfig(**TINY, lora_rank=2)
    lmodel = LlamaForCausalLM(cfg)
    lp = lmodel.init(
        jax.random.key(5), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    flat = extract_adapters(lp)
    assert all("lora_a" in f and "lora_b" in f for f in flat.values())
    base_tree = strip_adapters(lp)
    assert not extract_adapters(base_tree)
    ids = jnp.asarray([[5, 6, 7]], jnp.int32)
    merged = merge_adapter(base_tree, flat, alpha=16.0)
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": merged}, ids)),
        np.asarray(lmodel.apply({"params": lp}, ids)),
        rtol=1e-4, atol=1e-4,
    )


# ---------------------------------------------------------------------------
# admission / config errors
# ---------------------------------------------------------------------------


def test_tenant_admission_validation(base, adapters):
    model, params = base
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"]},
    )
    with pytest.raises(ValueError, match="unknown tenant"):
        session.submit(Request("x", [1, 2], 2, tenant="nobody"))
    plain = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2, paged=True
    )
    with pytest.raises(ValueError, match="serves no adapters"):
        plain.submit(Request("y", [1, 2], 2, tenant="t0"))
    with pytest.raises(ValueError, match="prefix_share"):
        ServeSession.from_model(
            model, params, prompt_len=PROMPT_LEN, num_slots=2,
            adapters={"t0": adapters["t0"]}, prefix_share=True,
        )
    with pytest.raises(ValueError, match="spec_k"):
        ServeSession.from_model(
            model, params, prompt_len=PROMPT_LEN, num_slots=2,
            adapters={"t0": adapters["t0"]}, spec_k=2,
        )


# ---------------------------------------------------------------------------
# migration: the tenant id rides the payload
# ---------------------------------------------------------------------------


def test_migration_repins_adapter_on_target(base, adapters):
    """Engine-level migration of a seated tenant request: the payload
    carries the tenant id, the target pool loads + pins the adapter
    before KV lands, and the resumed stream is byte-exact vs the
    merged reference."""
    from tpudl.models.generate import generate

    model, params = base
    mk = lambda: ServeSession.from_model(  # noqa: E731
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"], "t1": adapters["t1"]},
    )
    src, dst = mk(), mk()
    req = Request("mig", [9, 8, 7, 6], max_new_tokens=16, tenant="t0")
    src.submit(req)
    for _ in range(5):
        src.engine.step()
    payload = src.engine.export_request("mig")
    assert payload is not None
    from tpudl.serve.cache import parse_migration

    assert parse_migration(payload)["request"]["tenant"] == "t0"
    assert dst.engine.adapter_pool.resident_since("t0") is None
    dst.engine.install_migrated(payload)
    # Re-pinned BEFORE decode resumed; zero prefills on the target.
    assert dst.engine.adapter_pool.resident_since("t0") is not None
    assert dst.engine.num_prefills == 0
    while dst.engine.step():
        pass
    res = dst.engine.results["mig"]
    assert res.ok
    merged = merge_adapter(params, adapters["t0"])
    want = np.asarray(generate(
        model, merged, jnp.asarray([[9, 8, 7, 6]], jnp.int32),
        max_new_tokens=16,
    ))[0]
    np.testing.assert_array_equal(np.asarray(res.tokens), want)


def test_migration_refused_without_target_pool(base, adapters):
    """A tenant payload must NOT resume on an engine that cannot serve
    the tenant — it fails loudly instead of decoding the bare base."""
    from tpudl.serve.cache import MigrationCompatError

    model, params = base
    src = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"]},
    )
    dst = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2, paged=True
    )
    req = Request("m2", [4, 5, 6], max_new_tokens=8, tenant="t0")
    src.submit(req)
    for _ in range(3):
        src.engine.step()
    payload = src.engine.export_request("m2")
    with pytest.raises(MigrationCompatError, match="adapter pool"):
        dst.engine.install_migrated(payload)


# ---------------------------------------------------------------------------
# router: quotas, classes, affinity
# ---------------------------------------------------------------------------


def test_router_tenant_quota_and_priority(base, adapters):
    """Per-tenant classes on the existing priority ladder: the class
    priority is applied at the door, and the in-flight token quota
    sheds the excess as shed_quota."""
    from tpudl.serve import Replica, Router

    model, params = base
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"]},
    )
    # Warm so the replica thread never sits in a first-call compile.
    session.serve([Request("w", [1, 2], 2, tenant="t0")])
    router = Router(
        [Replica("r0", session)],
        tenant_classes={
            "t0": {"priority": 2, "max_inflight_tokens": 10}
        },
    )
    try:
        reqs = [
            Request(f"q{i}", [3, 4, 5], max_new_tokens=5, tenant="t0")
            for i in range(5)
        ]
        for r in reqs:
            router.submit(r)
        out = router.collect(timeout_s=120)
        reasons = sorted(r.finish_reason for r in out.values())
        assert reasons.count("shed_quota") == 3, reasons  # 2 fit 10 tokens
        served = [r for r in out.values() if r.ok]
        assert len(served) == 2
    finally:
        router.close()


def test_router_places_tenant_only_on_serving_replica(base, adapters):
    """Review regression: a heterogeneous fleet where only SOME
    replicas serve a tenant must route its requests there — the
    least-loaded fallback picking a non-serving replica would
    terminally reject them at the replica door."""
    from tpudl.serve import Replica, Router

    model, params = base
    s_plain = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2, paged=True
    )
    s_lora = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"]},
    )
    for s in (s_plain, s_lora):
        s.serve([Request("w", [1, 2], 2)])
    # The plain replica starts least-loaded AND first in the list.
    router = Router([Replica("plain", s_plain), Replica("lora", s_lora)])
    try:
        out = router.serve(
            [
                Request(f"t{i}", [4, 5], max_new_tokens=3, tenant="t0")
                for i in range(3)
            ],
            timeout_s=120,
        )
        assert all(r.ok for r in out.values()), {
            k: v.finish_reason for k, v in out.items()
        }
        assert s_lora.engine.adapter_pool.resident_since("t0") is not None
    finally:
        router.close()


def test_reregister_swaps_factors_and_refuses_leased(base, adapters):
    """Review regression: re-registering a tenant whose v1 pages are
    still cached (refcount 0) must invalidate them — the next acquire
    loads v2, not the stale pages the refreshed LRU stamp would keep
    alive. A LEASED residency refuses the swap."""
    model, _ = base
    pool = AdapterPool(model.cfg, r_max=2, num_slots=2, num_pages=9)
    pool.register("t", adapters["t0"])
    row_v1, _ = pool.acquire("t")
    pool.release("t")  # cached at refcount 0
    del row_v1
    pool.register("t", adapters["t1"])  # v2
    assert pool.resident_since("t") is None, (
        "stale v1 residency must be invalidated by re-registration"
    )
    row_v2, _ = pool.acquire("t")
    # v2 really is what loaded: the first page's A row holds t1's
    # first rank column, not t0's.
    got = np.asarray(
        pool.pools["layer_0"]["q_proj"]["a"][int(row_v2[0])]
    )
    want = np.asarray(
        adapters["t1"]["model/layer_0/attention/q_proj"]["lora_a"]
    )[:, 0]
    np.testing.assert_array_equal(got, want)
    # Leased: the swap must refuse instead of ripping pages out from
    # under a seated request.
    with pytest.raises(ValueError, match="leased"):
        pool.register("t", adapters["t0"])
    pool.release("t")
    pool.register("t", adapters["t0"])  # refcount 0 again: fine


def test_seat_failure_releases_adapter_pin(base, adapters):
    """Review regression: a cache-seat exception between acquire and
    bind must release the tenant pin — a leaked refcount would make
    the adapter unevictable for the process lifetime."""
    model, params = base
    session = ServeSession.from_model(
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"]},
    )
    engine = session.engine
    pool = engine.adapter_pool
    orig_seat = engine.cache.seat

    def boom(*args, **kwargs):
        raise RuntimeError("injected seat failure")

    engine.cache.seat = boom
    session.submit(Request("x", [1, 2, 3], max_new_tokens=4, tenant="t0"))
    with pytest.raises(RuntimeError, match="injected seat failure"):
        engine.step()
    engine.cache.seat = orig_seat
    assert pool.stats()["leased"] == 0, (
        "the failed seat leaked its tenant pin"
    )
    # The adapter is still fully usable (and evictable) afterwards.
    pool.acquire("t0")
    pool.release("t0")


def test_router_adapter_affinity(base, adapters):
    """A tenant's requests stick to the replica whose pool already
    holds its adapter (longest-resident wins), instead of loading the
    adapter everywhere."""
    from tpudl.serve import Replica, Router

    model, params = base
    mk = lambda: ServeSession.from_model(  # noqa: E731
        model, params, prompt_len=PROMPT_LEN, num_slots=2,
        adapters={"t0": adapters["t0"], "t1": adapters["t1"]},
    )
    s0, s1 = mk(), mk()
    for s in (s0, s1):
        s.serve([Request("w", [1, 2], 2)])  # warm compile, no tenant
    # Make t0 resident on s1 ONLY, before the router exists.
    s1.engine.adapter_pool.acquire("t0")
    s1.engine.adapter_pool.release("t0")
    router = Router([Replica("r0", s0), Replica("r1", s1)])
    try:
        for i in range(4):
            router.submit(Request(
                f"a{i}", [2, 3, 4], max_new_tokens=3, tenant="t0"
            ))
        out = router.collect(timeout_s=120)
        assert all(r.ok for r in out.values())
        # Every t0 request must have landed on r1: r0's pool never
        # loaded the adapter.
        assert s0.engine.adapter_pool.resident_since("t0") is None
        assert s1.engine.adapter_pool.stats()["loads"] == 1
    finally:
        router.close()
