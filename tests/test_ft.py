"""tpudl.ft: async checkpointing (bounded stall, back-pressure, atomic
commit), corruption fallback, full resume state (rng + data position),
preemption handling, and the supervisor's elastic restart — the
fault-tolerance contract as tests (ISSUE 4)."""

import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.ft import chaos
from tpudl.ft import preemption as ft_preemption
from tpudl.ft.data import ResumableIterator
from tpudl.ft.manager import AsyncCheckpointManager
from tpudl.ft.store import (
    CheckpointCorruptError,
    CheckpointShapeError,
    CheckpointStore,
)
from tpudl.ft.supervisor import (
    RestartPolicy,
    Supervisor,
    SupervisorGaveUp,
    resume_run,
)
from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models.resnet import ResNetTiny
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    fit,
    make_classification_train_step,
)


def _tiny_state(seed=0, num_classes=4):
    model = ResNetTiny(num_classes=num_classes)
    return create_train_state(
        jax.random.key(seed),
        model,
        jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.05, momentum=0.9),
    )


def _batches(n, seed=7):
    return list(
        synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=4, num_batches=n,
            seed=seed,
        )
    )


def _leaves_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# store: atomic commit protocol
# ---------------------------------------------------------------------------


def test_store_commit_and_visibility(tmp_path):
    store = CheckpointStore(str(tmp_path / "ck"), max_to_keep=2)
    assert store.latest_step() is None
    assert store.write(3, [("a", np.arange(6, dtype=np.float32))])
    assert store.latest_step() == 3
    # Re-saving a committed step is a no-op, not corruption.
    assert not store.write(3, [("a", np.zeros(6, np.float32))])
    meta, arrays = store.read(3)
    np.testing.assert_array_equal(
        arrays["a"], np.arange(6, dtype=np.float32)
    )
    # Retention keeps the newest max_to_keep.
    store.write(5, [("a", np.ones(2, np.float32))])
    store.write(7, [("a", np.ones(2, np.float32))])
    store.retain()
    assert store.all_steps() == [5, 7]


def test_store_uncommitted_is_invisible(tmp_path):
    """A crash mid-save (staging dir, or a final-named dir without the
    COMMIT marker) must never become the 'latest' restore picks up."""
    store = CheckpointStore(str(tmp_path / "ck"))
    store.write(2, [("a", np.arange(4, dtype=np.int32))])
    # Crash shape 1: an abandoned staging dir.
    staged = store.stage(9)
    with open(os.path.join(staged, "payload.bin"), "wb") as f:
        f.write(b"partial")
    # Crash shape 2: a final-named dir that never got its marker.
    os.makedirs(store.step_dir(8))
    with open(os.path.join(store.step_dir(8), "payload.bin"), "wb") as f:
        f.write(b"torn")
    assert store.latest_step() == 2
    assert store.all_steps() == [2]
    reaped = store.gc_stale()
    assert len(reaped) == 2
    assert store.latest_step() == 2


def test_store_commit_marker_removal_hides_step(tmp_path):
    store = CheckpointStore(str(tmp_path / "ck"))
    store.write(1, [("a", np.zeros(2, np.float32))])
    store.write(4, [("a", np.ones(2, np.float32))])
    chaos.remove_commit_marker(str(tmp_path / "ck"), 4)
    assert store.latest_step() == 1


def test_store_truncation_detected(tmp_path):
    store = CheckpointStore(str(tmp_path / "ck"))
    store.write(1, [("a", np.arange(1024, dtype=np.float32))])
    chaos.truncate_checkpoint(str(tmp_path / "ck"), 1, keep_bytes=64)
    with pytest.raises(CheckpointCorruptError, match="truncated"):
        store.read(1)


def test_store_same_size_bitrot_detected(tmp_path):
    """In-place corruption that does NOT change the payload length must
    still be caught (checksum), not restored as garbage weights."""
    store = CheckpointStore(str(tmp_path / "ck"))
    store.write(1, [("a", np.arange(1024, dtype=np.float32))])
    payload = os.path.join(store.step_dir(1), "payload.bin")
    with open(payload, "r+b") as f:
        f.seek(512)
        f.write(b"\xff" * 16)  # same size, flipped bits
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        store.read(1)


# ---------------------------------------------------------------------------
# manager: full-resume round-trip, stall bound, back-pressure, fallback
# ---------------------------------------------------------------------------


def test_manager_roundtrip_full_resume_state(tmp_path):
    state = _tiny_state()
    rng = jax.random.key(123)
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        assert mgr.save(
            0, state, rng=rng, data_state={"epoch": 1, "offset": 5}
        )
        mgr.wait_until_finished()
        restored, r_rng, r_data = mgr.restore_full(_tiny_state(seed=9))
    _leaves_equal(state.params, restored.params)
    _leaves_equal(state.opt_state, restored.opt_state)
    if state.batch_stats is not None:
        _leaves_equal(state.batch_stats, restored.batch_stats)
    assert int(restored.step) == int(state.step)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(rng)),
        np.asarray(jax.random.key_data(r_rng)),
    )
    # The restored key SAMPLES identically, not just compares equal.
    np.testing.assert_array_equal(
        np.asarray(jax.random.uniform(rng, (3,))),
        np.asarray(jax.random.uniform(r_rng, (3,))),
    )
    assert r_data == {"epoch": 1, "offset": 5}


def test_async_save_stall_bounded_vs_sync(tmp_path, monkeypatch):
    """THE bounded-stall regression: with a chaos-injected slow disk,
    the on-step stall of an async save stays a small fraction of the
    synchronous save time (the write happens behind the step loop)."""
    delay = 0.5
    monkeypatch.setenv(chaos.ENV_IO_DELAY_S, str(delay))
    state = _tiny_state()
    with AsyncCheckpointManager(str(tmp_path / "async")) as mgr:
        t0 = time.perf_counter()
        mgr.save(1, state)
        async_stall = time.perf_counter() - t0
        t0 = time.perf_counter()
        mgr.save(2, state, block=True)  # the synchronous comparison
        sync_time = time.perf_counter() - t0
        mgr.wait_until_finished()
        assert mgr.all_steps() == [1, 2]
    assert sync_time >= delay
    # "<<": the async stall must not even be half the sync save (in
    # practice it is ~10ms of snapshot vs 500ms+ of delayed IO).
    assert async_stall < sync_time / 2
    assert async_stall < delay / 2


def test_backpressure_at_most_one_inflight(tmp_path, monkeypatch):
    delay = 0.3
    monkeypatch.setenv(chaos.ENV_IO_DELAY_S, str(delay))
    state = _tiny_state()
    with AsyncCheckpointManager(str(tmp_path / "bp")) as mgr:
        t0 = time.perf_counter()
        mgr.save(1, state)
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        mgr.save(2, state)  # must wait for save 1 to commit
        second = time.perf_counter() - t0
        mgr.wait_until_finished()
        assert mgr.all_steps() == [1, 2]
    assert first < delay / 2
    assert second >= delay * 0.5


def test_corrupt_latest_falls_back_to_previous(tmp_path):
    state = _tiny_state()
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        state2 = state.replace(step=jnp.asarray(2, jnp.int32))
        state4 = state.replace(step=jnp.asarray(4, jnp.int32))
        mgr.save(2, state2, data_state={"epoch": 0, "offset": 2})
        mgr.save(4, state4, data_state={"epoch": 0, "offset": 4})
        mgr.wait_until_finished()
        chaos.truncate_checkpoint(mgr.directory, 4)
        # Explicit step: the corruption is the caller's business.
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(_tiny_state(seed=3), step=4)
        # Latest: walk back to the newest checkpoint that loads.
        with pytest.warns(UserWarning, match="corrupt"):
            restored, _, data = mgr.restore_full(_tiny_state(seed=3))
    assert int(restored.step) == 2
    assert data == {"epoch": 0, "offset": 2}


def test_restore_shape_mismatch_clear_error(tmp_path):
    """Changed model/topology: a clear per-leaf error, not a reshape
    crash (satellite 4) — on BOTH checkpoint backends."""
    from tpudl.checkpoint import CheckpointManager

    state = _tiny_state(num_classes=4)
    wrong = _tiny_state(seed=1, num_classes=7)
    with AsyncCheckpointManager(str(tmp_path / "a")) as mgr:
        mgr.save(0, state, block=True)
        with pytest.raises(CheckpointShapeError, match="head"):
            mgr.restore(wrong)
    with CheckpointManager(str(tmp_path / "o")) as omgr:
        omgr.save(0, state)
        omgr.wait_until_finished()
        with pytest.raises(CheckpointShapeError, match="head"):
            omgr.restore(wrong)


def test_restore_sharded_onto_mesh(mesh8, tmp_path):
    """Restore places leaves per FSDP rules on the 8-device mesh — the
    async store is sharding-aware like the Orbax path."""
    from tpudl.parallel.sharding import FSDP_RULES

    state = _tiny_state()
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        mgr.save(0, state, block=True)
        restored = mgr.restore(
            _tiny_state(seed=2), mesh=mesh8, rules=FSDP_RULES
        )
    _leaves_equal(state.params, restored.params)
    sharded = [
        leaf for leaf in jax.tree.leaves(restored.params)
        if hasattr(leaf, "sharding")
        and not leaf.sharding.is_fully_replicated
    ]
    assert sharded, "no parameter landed sharded under FSDP rules"


def test_writer_error_is_deferred_not_swallowed(tmp_path):
    state = _tiny_state()
    mgr = AsyncCheckpointManager(str(tmp_path / "ck"))
    # Make the store directory unwritable-ish by breaking the staging
    # root out from under the writer.
    mgr.save(1, state)
    mgr.wait_until_finished()
    import shutil

    shutil.rmtree(mgr.directory)
    with open(mgr.directory, "w") as f:  # a FILE where the dir was
        f.write("not a directory")
    mgr.save(2, state)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.wait_until_finished()
    os.remove(mgr.directory)


def test_save_train_state_crash_window_falls_back(tmp_path):
    """One-shot saves publish via staged rename; in the one crash
    window between the two renames the OLD checkpoint survives under
    the .tpudl-prev name and restore falls back to it (satellite:
    partial-write corruption)."""
    import os as _os

    from tpudl.checkpoint import restore_train_state, save_train_state

    state = _tiny_state()
    path = str(tmp_path / "ckpt")
    save_train_state(path, state)
    # Simulate the crash: the old dir was renamed aside, the staging
    # dir never made it to the final name.
    _os.rename(path, path + ".tpudl-prev")
    with pytest.warns(UserWarning, match="crashed mid-publish"):
        restored = restore_train_state(path, _tiny_state(seed=3))
    _leaves_equal(state.params, restored.params)
    # A later save cleans up and publishes normally.
    save_train_state(path, state)
    assert _os.path.exists(path)
    assert not _os.path.exists(path + ".tpudl-prev")


# ---------------------------------------------------------------------------
# resumable data position
# ---------------------------------------------------------------------------


def test_resumable_iterator_counts_and_seeks():
    it = ResumableIterator(iter(range(10)))
    assert [next(it) for _ in range(4)] == [0, 1, 2, 3]
    assert it.state() == {"epoch": 0, "offset": 4}
    it2 = ResumableIterator(list(range(10)))
    it2.seek({"epoch": 0, "offset": 4})
    assert next(it2) == 4
    with pytest.raises(ValueError, match="epoch"):
        ResumableIterator(list(range(3))).seek({"epoch": 2, "offset": 0})


def test_resumable_iterator_epoch_factory_rollover():
    factory = lambda epoch: [(epoch, i) for i in range(3)]  # noqa: E731
    it = ResumableIterator(factory, epochs=2)
    out = list(it)
    assert out == [(0, 0), (0, 1), (0, 2), (1, 0), (1, 1), (1, 2)]
    assert it.state() == {"epoch": 1, "offset": 3}
    it2 = ResumableIterator(factory, epochs=2).seek(
        {"epoch": 1, "offset": 1}
    )
    assert list(it2) == [(1, 1), (1, 2)]


# ---------------------------------------------------------------------------
# fit() integration: full resume state + schedule-identical resume
# ---------------------------------------------------------------------------


def test_fit_resume_run_schedule_identical(tmp_path):
    """Kill/resume == uninterrupted, via fit's full-resume checkpoints:
    interrupted run's post-resume losses match the uninterrupted run's
    tail EXACTLY (params, momentum, step counter, rng key, and data
    position all round-trip; resume_run fast-forwards the data)."""
    mesh = make_mesh(MeshSpec(dp=-1))
    step_fn = make_classification_train_step()
    rng = jax.random.key(42)
    total = 8

    def run(state, batches, num_steps, mgr=None, every=0):
        step = compile_step(step_fn, mesh, state, None, donate_state=False)
        losses = []
        state, _, info = fit(
            step, state, batches, rng, num_steps=num_steps,
            log_every=1, logger=lambda i, m: losses.append(m["loss"]),
            checkpoint_manager=mgr, checkpoint_every=every,
        )
        return state, losses

    # Uninterrupted control.
    _, control = run(
        _tiny_state(), ResumableIterator(_batches(total)), total
    )

    # Interrupted at step 4 (the "kill" is abandoning the process
    # state; only the checkpoint dir survives).
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        _, head = run(
            _tiny_state(), ResumableIterator(_batches(total)), 4,
            mgr=mgr, every=2,
        )
        assert mgr.latest_step() == 4

    # "New process": fresh template, fresh manager, resume_run.
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr2:
        template = _tiny_state(seed=5)
        state, r_rng, batches, start = resume_run(
            mgr2, template, ResumableIterator(_batches(total))
        )
        assert start == 4
        assert r_rng is not None
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(r_rng)),
            np.asarray(jax.random.key_data(rng)),
        )
        step = compile_step(step_fn, mesh, state, None, donate_state=False)
        tail_losses = []
        fit(
            step, state, batches, r_rng, num_steps=total - start,
            log_every=1,
            logger=lambda i, m: tail_losses.append(m["loss"]),
            checkpoint_manager=mgr2, checkpoint_every=2,
        )
    assert head == pytest.approx(control[:4])
    # Bit-for-bit: the resumed schedule IS the uninterrupted schedule.
    assert tail_losses == control[4:]


def test_resume_run_plain_iterable_keeps_position(tmp_path):
    """resume_run wraps plain iterables in a ResumableIterator (cold
    start AND resume), so the data position stays recorded across
    REPEATED restarts — the second resume must not rewind to batch 0."""
    mesh = make_mesh(MeshSpec(dp=-1))
    step_fn = make_classification_train_step()
    all_batches = _batches(8)

    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        state, rng, batches, start = resume_run(
            mgr, _tiny_state(), list(all_batches)
        )
        assert start == 0 and rng is None
        assert isinstance(batches, ResumableIterator)
        step = compile_step(step_fn, mesh, state, None, donate_state=False)
        fit(
            step, state, batches, jax.random.key(0), num_steps=3,
            checkpoint_manager=mgr, checkpoint_every=2,
        )

    # Restart 1: plain iterable again; position must fast-forward.
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr2:
        state, rng, batches, start = resume_run(
            mgr2, _tiny_state(seed=2), list(all_batches)
        )
        assert start == 3
        assert batches.state() == {"epoch": 0, "offset": 3}
        step = compile_step(step_fn, mesh, state, None, donate_state=False)
        fit(
            step, state, batches, rng, num_steps=2,
            checkpoint_manager=mgr2, checkpoint_every=2,
        )

    # Restart 2: the position recorded BY THE RESUMED RUN is correct
    # (this is what the islice wrap used to lose).
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr3:
        _, _, data = mgr3.restore_full(_tiny_state(seed=3))
        assert data == {"epoch": 0, "offset": 5}
        _, _, batches, start = resume_run(
            mgr3, _tiny_state(seed=3), list(all_batches)
        )
        assert start == 5
        assert batches.state() == {"epoch": 0, "offset": 5}


def test_fit_saves_data_position(tmp_path):
    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        mesh = make_mesh(MeshSpec(dp=-1))
        state = _tiny_state()
        step = compile_step(
            make_classification_train_step(), mesh, state, None,
            donate_state=False,
        )
        fit(
            step, state, ResumableIterator(_batches(5)),
            jax.random.key(0), checkpoint_manager=mgr, checkpoint_every=2,
        )
        _, rng, data = mgr.restore_full(_tiny_state(seed=1))
    assert rng is not None
    assert data == {"epoch": 0, "offset": 5}


def test_fit_resume_with_orbax_backend_sidecar(tmp_path):
    """The Orbax-backed CheckpointManager carries the same full resume
    state through its sidecar (fit -> restore_full round-trip)."""
    from tpudl.checkpoint import CheckpointManager

    with CheckpointManager(str(tmp_path / "ck")) as mgr:
        mesh = make_mesh(MeshSpec(dp=-1))
        state = _tiny_state()
        step = compile_step(
            make_classification_train_step(), mesh, state, None,
            donate_state=False,
        )
        fit(
            step, state, ResumableIterator(_batches(3)),
            jax.random.key(9), checkpoint_manager=mgr,
            checkpoint_every=2,
        )
        restored, rng, data = mgr.restore_full(_tiny_state(seed=1))
    assert int(restored.step) == 3
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(rng)),
        np.asarray(jax.random.key_data(jax.random.key(9))),
    )
    assert data == {"epoch": 0, "offset": 3}


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------


def test_preemption_triggers_emergency_checkpoint(tmp_path):
    """SIGTERM mid-fit: the loop stops, the emergency checkpoint
    commits at the interrupted step, info says preempted, and the
    grace watchdog is disarmed on the cooperative path."""
    ft_preemption.reset()
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _tiny_state()
    step = compile_step(
        make_classification_train_step(), mesh, state, None,
        donate_state=False,
    )

    def send_sigterm(i, metrics):
        if i == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    with AsyncCheckpointManager(str(tmp_path / "ck")) as mgr:
        with ft_preemption.PreemptionGuard(grace_s=60.0):
            state, _, info = fit(
                step, state, ResumableIterator(_batches(10)),
                jax.random.key(0), log_every=1, logger=send_sigterm,
                checkpoint_manager=mgr, checkpoint_every=100,
            )
            assert ft_preemption.requested()
            assert ft_preemption.remaining_grace() > 0
        latest = mgr.latest_step()
    assert info["preempted"] is True
    assert info["steps"] == 3
    assert latest == 3
    # The guard's exit cleared the flag: a later fit() in this process
    # must not silently train 0 steps as "preempted".
    assert not ft_preemption.requested()


def test_preemption_guard_restores_handlers():
    ft_preemption.reset()
    before = signal.getsignal(signal.SIGTERM)
    with ft_preemption.PreemptionGuard(grace_s=1.0):
        assert signal.getsignal(signal.SIGTERM) is not before
    assert signal.getsignal(signal.SIGTERM) is before


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


class _FlakyDistributor:
    """Fails the first ``fail_times`` cohort launches, then succeeds."""

    def __init__(self, fail_times):
        self.fail_times = fail_times
        self.launches = 0

    def run(self, fn, *args, **kwargs):
        self.launches += 1
        if self.launches <= self.fail_times:
            raise RuntimeError(
                f"TpuDistributor: 1/2 worker(s) failed (launch "
                f"{self.launches})"
            )
        return [fn(*args, **kwargs)]


def test_supervisor_restarts_until_success():
    sleeps = []
    d = _FlakyDistributor(fail_times=2)
    sup = Supervisor(
        d,
        policy=RestartPolicy(
            max_restarts=3, backoff_s=0.01, backoff_factor=2.0,
            max_backoff_s=10.0,
        ),
        sleep=sleeps.append,
    )
    assert sup.run(lambda x: x * 2, 21) == [42]
    assert d.launches == 3
    assert sup.restarts == 2
    assert sleeps == [0.01, 0.02]  # exponential backoff


def test_supervisor_retry_budget_exhausted():
    d = _FlakyDistributor(fail_times=99)
    sup = Supervisor(
        d, policy=RestartPolicy(max_restarts=2, backoff_s=0.0),
        sleep=lambda s: None,
    )
    with pytest.raises(SupervisorGaveUp, match="retry budget"):
        sup.run(lambda: 1)
    assert d.launches == 3  # initial + 2 restarts


def test_supervisor_nonrestartable_fails_fast():
    class _Bad:
        def run(self, fn, *a, **k):
            raise TypeError("programming error, do not retry")

    sup = Supervisor(_Bad(), sleep=lambda s: None)
    with pytest.raises(TypeError):
        sup.run(lambda: 1)


# ---------------------------------------------------------------------------
# distributor failure classification (formatting unit; spawn paths are
# exercised by the slow tests in test_ft_elastic.py)
# ---------------------------------------------------------------------------


def test_worker_failure_report_classifies_and_includes_survivors():
    from tpudl.runtime.distributor import WorkerFailedError, WorkerFailure

    err = WorkerFailedError(
        4,
        [
            WorkerFailure(1, "exit", "no result file\n<log>",
                          returncode=-9, signal=9),
            WorkerFailure(2, "exception", "worker exception: Boom"),
        ],
        {0: "rank0 was fine until the collective", 3: "rank3 tail"},
    )
    msg = str(err)
    assert "2/4 worker(s) failed" in msg
    assert "signal SIGKILL" in msg
    assert "exception" in msg and "Boom" in msg
    assert "surviving-worker log tails" in msg
    assert "rank0 was fine" in msg and "rank3 tail" in msg
    assert isinstance(err, RuntimeError)  # legacy catch sites still work


# ---------------------------------------------------------------------------
# obs: lost-to-recovery goodput + overlapped background writes
# ---------------------------------------------------------------------------


def test_goodput_recovery_and_background_write_classification():
    from tpudl.obs import goodput as obs_goodput
    from tpudl.obs import spans as obs_spans

    def span(cat, ts, dur):
        return {
            "kind": "span", "name": cat, "cat": cat, "ts": ts,
            "dur": dur, "host": "h", "process": 0, "pid": 1, "tid": 1,
        }

    recs = [
        span(obs_spans.CAT_STEP, 0.0, 2.0),
        span(obs_spans.CAT_RECOVERY, 2.0, 1.0),
        # Background write OVERLAPS the steps and extends the window:
        # reported, never accounted (else idle would go negative).
        span(obs_spans.CAT_CKPT_BG, 0.0, 4.0),
    ]
    cls = obs_goodput.classify(recs)
    np.testing.assert_allclose(cls["wall_s"], 4.0)
    np.testing.assert_allclose(cls["recovery_s"], 1.0)
    np.testing.assert_allclose(cls["productive_s"], 2.0)
    np.testing.assert_allclose(cls["idle_s"], 1.0)
    np.testing.assert_allclose(cls["goodput"], 0.5)
    line = obs_goodput.format_goodput(cls)
    assert "recovery" in line
