"""Fused short-seq attention + fused softmax-dropout kernels.

CPU tier (interpret mode): exact-shape parity for every masking mode at
dropout 0 — the PRNG-backed dropout paths are TPU-only (interpret mode
has no PRNG emulation; asserted here) and get their statistical checks
on the real chip via benchmarks/bert_attn_seq128.py and the TPU
subprocess check in scripts/tpu_dropout_check.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.attention import attend, causal_mask, padding_mask
from tpudl.ops.fused_attention import fused_attention
from tpudl.ops.softmax_dropout import hybrid_attention, softmax_dropout


def _qkv(seed, b=2, s=96, h=4, d=32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks
    )


def _padding(seed, b, s):
    lengths = jax.random.randint(jax.random.key(seed), (b,), s // 2, s + 1)
    return (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)


@pytest.mark.parametrize("impl", ["fused_kernel", "hybrid"])
def test_matches_reference_no_mask(impl):
    q, k, v = _qkv(0)
    fn = fused_attention if impl == "fused_kernel" else hybrid_attention
    np.testing.assert_allclose(
        np.asarray(fn(q, k, v)), np.asarray(attend(q, k, v)), atol=2e-4
    )


@pytest.mark.parametrize("impl", ["fused_kernel", "hybrid"])
def test_matches_reference_padding_and_causal(impl):
    q, k, v = _qkv(1)
    am = _padding(2, 2, 96)
    expected = attend(
        q, k, v,
        mask=jnp.logical_and(padding_mask(am), causal_mask(96, 96)),
    )
    fn = fused_attention if impl == "fused_kernel" else hybrid_attention
    got = fn(q, k, v, mask=am, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4)


@pytest.mark.parametrize("impl", ["fused_kernel", "hybrid"])
def test_grads_match_reference(impl):
    q, k, v = _qkv(3)
    am = _padding(4, 2, 96)
    fn = fused_attention if impl == "fused_kernel" else hybrid_attention

    def loss_ref(q, k, v):
        return jnp.sum(attend(q, k, v, mask=padding_mask(am)) ** 2)

    def loss_fused(q, k, v):
        return jnp.sum(fn(q, k, v, mask=am) ** 2)

    g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    g_f = jax.grad(loss_fused, (0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_f):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-4)


def test_softmax_dropout_matches_jax_softmax():
    logits = jax.random.normal(jax.random.key(5), (2, 4, 64, 96)) * 4
    got = softmax_dropout(logits, out_dtype=jnp.float32)
    want = jax.nn.softmax(logits, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_softmax_dropout_masks_and_pads():
    # Non-128-multiple Skv exercises the padded-columns masking.
    logits = jax.random.normal(jax.random.key(6), (2, 2, 40, 72))
    am = _padding(7, 2, 72)
    got = softmax_dropout(logits, mask=am, out_dtype=jnp.float32)
    masked = jnp.where(
        padding_mask(am), logits.astype(jnp.float32), -jnp.inf
    )
    want = jax.nn.softmax(masked, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_softmax_dropout_grad_matches():
    logits = jax.random.normal(jax.random.key(8), (2, 2, 64, 64))

    def f_k(x):
        return jnp.sum(softmax_dropout(x, out_dtype=jnp.float32) ** 2)

    def f_r(x):
        return jnp.sum(jax.nn.softmax(x, axis=-1) ** 2)

    np.testing.assert_allclose(
        np.asarray(jax.grad(f_k)(logits)),
        np.asarray(jax.grad(f_r)(logits)),
        atol=1e-6,
    )


def test_attend_dispatches_fused():
    q, k, v = _qkv(9, s=64)
    got = attend(q, k, v, implementation="fused")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(attend(q, k, v)), atol=2e-4
    )
    # Mid-seq branch routes to the whole-attention kernel.
    q2, k2, v2 = _qkv(10, s=384, h=2)
    got2 = attend(q2, k2, v2, implementation="fused", causal=True)
    want2 = attend(q2, k2, v2, mask=causal_mask(384, 384))
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), atol=2e-4)
    # Past MAX_SEQ: flash takes over, WITH in-kernel dropout (round-4; on
    # the CPU interpret path that surfaces as the no-hardware-PRNG
    # refusal rather than the round-3 unconditional ValueError).
    q3, k3, v3 = _qkv(11, s=640, h=2)
    got3 = attend(q3, k3, v3, implementation="fused")
    np.testing.assert_allclose(
        np.asarray(got3), np.asarray(attend(q3, k3, v3)), atol=2e-4
    )
    with pytest.raises(NotImplementedError, match="hardware PRNG"):
        attend(q3, k3, v3, implementation="fused", dropout_rate=0.1,
               dropout_rng=jax.random.key(0))


def test_in_kernel_dropout_requires_tpu():
    q, k, v = _qkv(11, s=64)
    with pytest.raises(NotImplementedError, match="TPU"):
        fused_attention(
            q, k, v, dropout_rate=0.1, dropout_rng=jax.random.key(0)
        )
    with pytest.raises(NotImplementedError, match="TPU"):
        softmax_dropout(
            jnp.zeros((1, 1, 64, 64)), dropout_rate=0.1,
            dropout_rng=jax.random.key(0),
        )


def test_validation():
    q, k, v = _qkv(12, s=64)
    with pytest.raises(ValueError, match="dropout_rng"):
        fused_attention(q, k, v, dropout_rate=0.1)
    with pytest.raises(ValueError, match="head_group"):
        fused_attention(q, k, v, head_group=3)
    big = jnp.zeros((1, 2048, 2, 32))
    with pytest.raises(ValueError, match="flash"):
        fused_attention(big, big, big)
    with pytest.raises(ValueError, match="Sq == Skv"):
        fused_attention(q, k[:, :32], v[:, :32])
