"""tpudl.obs.requestlog + tpudl.obs.metering: the durable request log
and the per-tenant metering plane (ISSUE 16).

The contract under test: every terminal Result leaves exactly one
versioned-schema JSONL record in crc-committed rotated segments; the
writer's bounded queue never blocks (overflow is counted, not waited
out); the reader recovers every committed record across rotation and
past a truncated tail (loudly), raises on non-tail corruption, and
checkpoints/restores its position with the ft.data.ResumableIterator
state dict; and the per-tenant rollups the meter renders (and the
report CLI tabulates) reconcile EXACTLY with the live Results.
"""

import json
import os
import threading
import zlib

import numpy as np
import pytest

from tpudl.analysis.registry import KNOBS
from tpudl.ft.data import resumable_request_log
from tpudl.obs import counters as obs_counters
from tpudl.obs import metering, requestlog
from tpudl.obs import report as obs_report


@pytest.fixture(autouse=True)
def _clean_requestlog(monkeypatch):
    """Writer + meter + registry are process-global; isolate every
    test (the span-stream _clean_obs idiom, extended)."""
    monkeypatch.delenv("TPUDL_OBS_DIR", raising=False)
    monkeypatch.delenv("TPUDL_OBS_REQUEST_LOG", raising=False)
    requestlog.disable()
    metering.meter().reset()
    obs_counters.registry().reset()
    yield
    requestlog.disable()
    metering.meter().reset()
    obs_counters.registry().reset()


def _rec(i, tenant=None, finish_reason="eos", **kw):
    kw.setdefault("tokens_in", 3)
    kw.setdefault("tokens_out", 5)
    kw.setdefault("ts", float(i))
    return requestlog.build_record(
        f"r{i}", finish_reason, tenant=tenant, **kw
    )


def _ids(records):
    return [r["request_id"] for r in records]


# ---------------------------------------------------------------------------
# writer: rotation, commit-or-invisible, restart
# ---------------------------------------------------------------------------


def test_rotation_roundtrip(tmp_path):
    """N records across a forced rotation boundary come back in order,
    every segment committed with its crc32 in the name."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=256)
    for i in range(20):
        w.log(_rec(i))
    w.close()
    assert w.dropped == 0 and w.written == 20

    segs = requestlog.list_segments(d)
    assert len(segs) >= 2, "segment_bytes=256 must force a rotation"
    assert w.segments_committed == len(segs)
    for idx, crc, path in segs:
        assert crc is not None, f"uncommitted segment survived: {path}"
        with open(path, "rb") as f:
            assert (zlib.crc32(f.read()) & 0xFFFFFFFF) == crc
    assert [idx for idx, _, _ in segs] == sorted(
        idx for idx, _, _ in segs
    )

    records = list(requestlog.read_request_log(d))
    assert _ids(records) == [f"r{i}" for i in range(20)]
    assert all(r["v"] == requestlog.SCHEMA_VERSION for r in records)


def test_close_commits_open_tail(tmp_path):
    """close() publishes the partial tail segment: after close there
    is no .open file left and every record is crc-guarded."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    for i in range(3):
        w.log(_rec(i))
    w.close()
    names = os.listdir(d)
    assert not any(n.endswith(".open.jsonl") for n in names), names
    assert _ids(list(requestlog.read_request_log(d))) == [
        "r0", "r1", "r2"
    ]
    w.close()  # idempotent


def test_restart_never_appends_into_old_segments(tmp_path):
    """A new writer starts past the highest index on disk — a restart
    cannot touch (or recommit) a previous process's segments."""
    d = str(tmp_path)
    w1 = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    for i in range(3):
        w1.log(_rec(i))
    w1.close()
    first = {idx for idx, _, _ in requestlog.list_segments(d)}

    w2 = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    for i in range(3, 5):
        w2.log(_rec(i))
    w2.close()
    segs = requestlog.list_segments(d)
    assert {idx for idx, _, _ in segs} > first
    assert _ids(list(requestlog.read_request_log(d))) == [
        f"r{i}" for i in range(5)
    ]


def test_overflow_drops_counted_never_blocks(tmp_path):
    """With the writer thread wedged mid-write, a full queue drops (and
    counts) instead of blocking the caller — the decode loop never
    waits on disk."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, queue_depth=2)
    entered, gate = threading.Event(), threading.Event()
    orig = w._write_one

    def wedged(rec):
        entered.set()
        gate.wait(timeout=30.0)
        orig(rec)

    w._write_one = wedged
    try:
        w.log(_rec(0))
        assert entered.wait(timeout=10.0)  # thread holds r0, blocked
        w.log(_rec(1))
        w.log(_rec(2))  # queue now full (depth 2)
        w.log(_rec(3))  # must return immediately, counted as dropped
        w.log(_rec(4))
        assert w.dropped == 2
        assert (
            obs_counters.registry()
            .counter("requestlog_records_dropped").value == 2
        )
    finally:
        gate.set()
    w.close()
    assert _ids(list(requestlog.read_request_log(d))) == [
        "r0", "r1", "r2"
    ]
    assert w.written == 3


# ---------------------------------------------------------------------------
# reader: tail recovery, non-tail corruption, position resume
# ---------------------------------------------------------------------------


def test_truncated_open_tail_recovered_with_warning(tmp_path):
    """A torn .open tail (crash before commit) yields every intact
    record before the tear, with a loud RuntimeWarning — never silent
    loss, never a crash."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    for i in range(5):
        w.log(_rec(i))
    w.flush()  # on disk, still .open (uncommitted — crash imminent)
    opens = [n for n in os.listdir(d) if n.endswith(".open.jsonl")]
    assert len(opens) == 1
    path = os.path.join(d, opens[0])
    blob = open(path, "rb").read()
    with open(path, "wb") as f:  # tear mid final record
        f.write(blob[:-7])

    with pytest.warns(RuntimeWarning, match="truncated"):
        records = list(requestlog.read_request_log(d))
    assert _ids(records) == [f"r{i}" for i in range(4)]
    w.close()


def _write_raw_segment(d, idx, records, tail=b"", committed=False):
    blob = b"".join(
        (json.dumps(r) + "\n").encode("utf-8") for r in records
    ) + tail
    if committed:
        crc = zlib.crc32(blob) & 0xFFFFFFFF
        name = f"requests-{idx:06d}-{crc:08x}.jsonl"
    else:
        name = f"requests-{idx:06d}.open.jsonl"
    with open(os.path.join(d, name), "wb") as f:
        f.write(blob)


def test_orphan_open_mid_log_tolerated_by_reader(tmp_path):
    """Tail tolerance follows COMMITMENT, not position: a crashed
    process's torn .open segment stays readable (intact prefix, loud
    warning) even once a restarted writer has published newer segments
    behind it — it must never flip the whole log to
    RequestLogCorruptError."""
    d = str(tmp_path)
    _write_raw_segment(
        d, 0, [_rec(i) for i in range(3)], tail=b'{"torn'
    )
    _write_raw_segment(
        d, 1, [_rec(i) for i in range(3, 6)], committed=True
    )
    with pytest.warns(RuntimeWarning, match="truncated"):
        records = list(requestlog.read_request_log(d))
    assert _ids(records) == [f"r{i}" for i in range(6)]


def test_restart_seals_orphan_open_segment(tmp_path):
    """A new writer crc-seals a predecessor's orphaned .open segment on
    startup — torn final line trimmed loudly, intact records upgraded
    to full crc protection, nothing left uncommitted mid-log."""
    d = str(tmp_path)
    _write_raw_segment(
        d, 0, [_rec(i) for i in range(3)], tail=b'{"torn'
    )
    with pytest.warns(RuntimeWarning, match="torn record"):
        w = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    w.log(_rec(3))
    w.close()
    assert not any(
        n.endswith(".open.jsonl") for n in os.listdir(d)
    )
    segs = requestlog.list_segments(d)
    assert [idx for idx, _, _ in segs] == [0, 1]
    for _, crc, path in segs:
        assert crc is not None
        with open(path, "rb") as f:
            assert (zlib.crc32(f.read()) & 0xFFFFFFFF) == crc
    assert (
        obs_counters.registry()
        .counter("requestlog_orphans_sealed").value == 1
    )
    # Fully committed now: reading warns about nothing.
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        records = list(requestlog.read_request_log(d))
    assert _ids(records) == ["r0", "r1", "r2", "r3"]


def test_damaged_committed_tail_recovers_prefix(tmp_path):
    """A committed TAIL whose crc no longer matches degrades to loud
    line-by-line recovery instead of raising."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    for i in range(4):
        w.log(_rec(i))
    w.close()
    _, crc, path = requestlog.list_segments(d)[-1]
    assert crc is not None
    with open(path, "ab") as f:
        f.write(b'{"torn')  # crc mismatch + unparsable final line
    with pytest.warns(RuntimeWarning, match="truncated"):
        records = list(requestlog.read_request_log(d))
    assert _ids(records) == [f"r{i}" for i in range(4)]


def test_non_tail_corruption_raises(tmp_path):
    """Damage in the MIDDLE of the log is the unforgivable case: the
    reader raises RequestLogCorruptError, it does not skip."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=128)
    for i in range(12):
        w.log(_rec(i))
    w.close()
    segs = requestlog.list_segments(d)
    assert len(segs) >= 2
    _, _, first_path = segs[0]
    blob = bytearray(open(first_path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(first_path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(requestlog.RequestLogCorruptError):
        list(requestlog.read_request_log(d))


def test_reader_position_resume(tmp_path):
    """state()/seek() round-trip: a fresh reader seeked to a saved
    position consumes exactly the not-yet-consumed suffix — no repeat,
    no gap — and the state dict drives ft.data.resumable_request_log
    identically."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=256)
    for i in range(8):
        w.log(_rec(i))
    w.close()
    assert len(requestlog.list_segments(d)) >= 2

    r1 = requestlog.RequestLogReader(d)
    head = [next(r1) for _ in range(3)]
    st = r1.state()
    assert set(st) == {"epoch", "offset"}

    r2 = requestlog.RequestLogReader(d)
    r2.seek(st)
    tail = list(r2)
    assert _ids(head + tail) == [f"r{i}" for i in range(8)]

    # The ft.data iterator speaks the same position dialect.
    it = resumable_request_log(d)
    assert _ids(list(it)) == [f"r{i}" for i in range(8)]
    it2 = resumable_request_log(d)
    it2.seek(st)
    assert _ids(list(it2)) == [f"r{i}" for i in range(3, 8)]
    # And a position taken on the ft.data side seeks the log reader.
    it3 = resumable_request_log(d)
    for _ in range(5):
        next(it3)
    r3 = requestlog.RequestLogReader(d)
    r3.seek(it3.state())
    assert _ids(list(r3)) == [f"r{i}" for i in range(5, 8)]


def test_seek_past_reaped_segment_is_empty_epoch(tmp_path):
    """Sparse indices (operator-deleted / GC-reaped segments) keep
    positions meaningful: an absent epoch is empty, not an error."""
    d = str(tmp_path)
    w = requestlog.RequestLogWriter(d, segment_bytes=128)
    for i in range(12):
        w.log(_rec(i))
    w.close()
    segs = requestlog.list_segments(d)
    assert len(segs) >= 3
    idx0, _, path0 = segs[0]
    n0 = len(requestlog.segment_records(path0, segs[0][1], False))
    os.remove(path0)
    records = list(requestlog.read_request_log(d))
    assert _ids(records) == [f"r{i}" for i in range(n0, 12)]
    it = resumable_request_log(d)
    it.seek({"epoch": idx0, "offset": 0})
    assert _ids(list(it)) == [f"r{i}" for i in range(n0, 12)]


# ---------------------------------------------------------------------------
# activation: env knob, enable/disable, log_result chokepoint
# ---------------------------------------------------------------------------


def test_knobs_declared():
    for name in (
        "TPUDL_OBS_REQUEST_LOG",
        "TPUDL_OBS_REQUEST_LOG_SEGMENT_BYTES",
        "TPUDL_OBS_REQUEST_LOG_QUEUE",
    ):
        assert name in KNOBS, f"{name} missing from the knob registry"


def test_env_auto_enable_and_knob_sizes(tmp_path, monkeypatch):
    d = str(tmp_path / "rlog")
    monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG", d)
    monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG_SEGMENT_BYTES", "512")
    monkeypatch.setenv("TPUDL_OBS_REQUEST_LOG_QUEUE", "7")
    assert requestlog.active_writer() is not None
    w = requestlog.active_writer()
    assert w.directory == d
    assert w.segment_bytes == 512
    assert w._queue.maxsize == 7
    requestlog.log_result(_rec(0, tenant="a"))
    requestlog.disable()
    assert _ids(list(requestlog.read_request_log(d))) == ["r0"]
    # The chokepoint fed the meter too — same record, same counts.
    assert metering.meter().tenants()["a"]["requests_total"] == 1


def test_log_result_without_writer_still_meters():
    requestlog.log_result(_rec(0, tenant="b", finish_reason="shed_quota"))
    assert requestlog.active_writer() is None
    t = metering.meter().tenants()["b"]
    assert t["requests_total"] == 1
    assert t["sheds"] == {"shed_quota": 1}


# ---------------------------------------------------------------------------
# metering: rollups, render, exporter integration
# ---------------------------------------------------------------------------


def test_meter_rollup_and_shed_bucketing():
    m = metering.TenantMeter()
    m.ingest(_rec(0, tenant="a", tokens_out=7, active_s=2.0,
                  kv_byte_seconds=10.0, adapter_reloads=1))
    m.ingest(_rec(1, tenant="a", finish_reason="shed_slo"))
    m.ingest(_rec(2, tenant="a",
                  finish_reason="failed: RuntimeError: boom"))
    m.ingest(_rec(3))  # tenant None -> _base
    snap = m.tenants()
    a = snap["a"]
    assert a["requests_total"] == 3
    assert a["requests_completed"] == 1
    assert a["tokens_out"] == 7 + 5 + 5
    assert a["sheds"] == {"shed_slo": 1, "failed": 1}
    assert a["chip_seconds"] == pytest.approx(2.0)
    assert a["adapter_residency_s"] == pytest.approx(2.0)
    assert a["adapter_reloads"] == 1
    base = snap[metering.BASE_TENANT]
    assert base["requests_total"] == 1
    # Base-model requests hold no adapter: residency stays 0.
    assert base["adapter_residency_s"] == 0.0


def test_meter_free_text_reasons_stay_closed_set():
    """Both free-text finish_reason families — ``failed: <exc>`` from
    the engine and ``rejected: <exc>`` from the router — collapse to
    ONE sheds bucket each: the Prometheus metric names render() mints
    from sheds keys must not grow per distinct exception message."""
    m = metering.TenantMeter()
    m.ingest(_rec(0, tenant="a",
                  finish_reason="rejected: ValueError: too long"))
    m.ingest(_rec(1, tenant="a",
                  finish_reason="rejected: ValueError: duplicate id"))
    m.ingest(_rec(2, tenant="a",
                  finish_reason="failed: RuntimeError: boom"))
    m.ingest(_rec(3, tenant="a",
                  finish_reason="failed: OSError: disk"))
    assert m.tenants()["a"]["sheds"] == {"rejected": 2, "failed": 2}
    text = m.render()
    assert 'serve_tenant_requests_rejected{tenant="a"} 2' in text
    assert 'serve_tenant_requests_failed{tenant="a"} 2' in text
    assert "ValueError" not in text and "RuntimeError" not in text


def test_meter_render_tenant_labels():
    m = metering.TenantMeter()
    m.ingest(_rec(0, tenant="acme", tokens_out=9))
    m.set_quota_utilization("acme", 0.25)
    text = m.render()
    assert 'serve_tenant_requests_total{tenant="acme"} 1' in text
    assert 'serve_tenant_tokens_total{tenant="acme"} 9' in text
    assert 'serve_tenant_quota_utilization{tenant="acme"} 0.25' in text
    m.ingest(_rec(1, finish_reason="shed_capacity"))
    text = m.render()
    assert (
        'serve_tenant_requests_shed_capacity{tenant="_base"} 1' in text
    )


def test_exporter_appends_tenant_series():
    from tpudl.obs.exporter import ObsExporter

    ex = ObsExporter(port=0)
    clean = ex.metrics_text()
    assert "serve_tenant_" not in clean  # no tenants -> no extra bytes
    requestlog.log_result(_rec(0, tenant="t9"))
    text = ex.metrics_text()
    assert 'serve_tenant_requests_total{tenant="t9"} 1' in text
    assert '# TYPE serve_tenant_requests_total counter' in text


# ---------------------------------------------------------------------------
# report CLI: --tenants cost table, --request durable fallback
# ---------------------------------------------------------------------------


def _write_log(d, records):
    w = requestlog.RequestLogWriter(d, segment_bytes=1 << 20)
    for r in records:
        w.log(r)
    w.close()


def test_tenant_report_and_cli(tmp_path, capsys):
    d = str(tmp_path)
    _write_log(d, [
        _rec(0, tenant="a", tokens_out=10, active_s=3.0),
        _rec(1, tenant="b", tokens_out=4, active_s=1.0),
        _rec(2, tenant="b", finish_reason="shed_quota"),
    ])
    rep = obs_report.build_tenant_report(
        requestlog.read_request_log(d)
    )
    assert rep["records"] == 3
    assert rep["tenants"]["a"]["chip_share"] == pytest.approx(0.75)
    assert rep["tenants"]["b"]["chip_share"] == pytest.approx(0.25)
    table = obs_report.format_tenant_report(rep)
    assert "shed_quota=1" in table

    assert obs_report.main([d, "--tenants"]) == 0
    out = capsys.readouterr().out
    assert "a" in out and "total chip-seconds" in out

    # Run-dir convention: the log under <run>/requestlog resolves too.
    run = tmp_path / "run"
    os.makedirs(run / "requestlog")
    _write_log(str(run / "requestlog"), [_rec(9, tenant="z")])
    assert obs_report.load_request_records([str(run)])[0][
        "request_id"
    ] == "r9"

    assert obs_report.main([str(tmp_path / "empty"), "--tenants"]) == 1


def test_request_cli_durable_fallback(tmp_path, capsys):
    """--request with the span stream gone falls back to the durable
    terminal record instead of erroring."""
    d = str(tmp_path)
    _write_log(d, [_rec(7, tenant="a", finish_reason="length")])
    assert obs_report.find_request_record([d], "r7")["tenant"] == "a"
    assert obs_report.find_request_record([d], "nope") is None
    assert obs_report.main([d, "--request", "r7"]) == 0
    out = capsys.readouterr().out
    assert "durable record" in out and "finish_reason=length" in out
    assert obs_report.main([d, "--request", "nope"]) == 1


def test_span_report_ignores_request_log_segments(tmp_path):
    """A request log nested under an obs dir must not be ingested as
    span records by the span loader's recursive glob: with only
    requests-*.jsonl segments present, the SPAN loader sees no span
    files at all."""
    _write_log(str(tmp_path / "requestlog"), [_rec(0)])
    with pytest.raises(FileNotFoundError, match="no .*jsonl"):
        obs_report.load_records([str(tmp_path)])


# ---------------------------------------------------------------------------
# train numerics telemetry (satellite: loss scale / grad skips / fp8)
# ---------------------------------------------------------------------------


def test_publish_numerics_telemetry():
    from tpudl.train.precision import publish_numerics_telemetry

    publish_numerics_telemetry(None)  # f32 runs pay nothing
    reg = obs_counters.registry()
    assert "train_loss_scale" not in reg.snapshot().get("gauges", {})

    state = {
        "loss_scale": {
            "scale": np.float32(1024.0),
            "skipped": np.int32(3),
        },
        "fp8": {
            "dense": {"x_hist": np.array([2.0, 1.0], np.float32),
                      "x_scale": np.float32(1.0)},
        },
    }
    publish_numerics_telemetry(state)
    snap = reg.snapshot()
    assert snap["gauges"]["train_loss_scale"] == 1024.0
    assert snap["counters"]["train_grad_skipped_total"] == 3
    # Cumulative source, delta-advanced counter: a re-publish of the
    # same state must NOT double-count.
    publish_numerics_telemetry(state)
    assert (
        reg.snapshot()["counters"]["train_grad_skipped_total"] == 3
    )
    h = reg.snapshot()["histograms"]["train_fp8_amax_drift"]
    assert h["count"] == 2  # one ring observed per publish
    assert h["max"] == pytest.approx(0.5)  # (2 - 1) / 2


# ---------------------------------------------------------------------------
# end to end: serve with the log on, reconcile tenants exactly
# ---------------------------------------------------------------------------


def test_end_to_end_multitenant_reconciliation(tmp_path):
    """The acceptance bar: a multi-tenant serve across a forced
    rotation boundary leaves one record per Result, zero drops, and
    per-tenant token sums from the READER equal to the live Results —
    and the live meter agrees."""
    from benchmarks.serve_load import run_requestlog_roundtrip

    out = run_requestlog_roundtrip(
        log_dir=str(tmp_path), n_tenants=2, per_tenant=3,
        num_slots=2, segment_bytes=1024,
    )
    assert out["reconciled"]
    assert out["dropped"] == 0
    assert out["segments"] >= 2
    records = [
        r for r in requestlog.read_request_log(str(tmp_path))
        if str(r["request_id"]).startswith("rlog-")
    ]
    assert len(records) == out["requests"]
    for r in records:
        assert r["v"] == requestlog.SCHEMA_VERSION
        assert r["site"] == "engine"
        assert r["finish_reason"] in ("eos", "length")
        assert r["tenant"] is not None
        assert r["tokens_out"] > 0
        assert r["active_s"] >= 0.0
        assert r["kv_page_seconds"] >= 0.0
    snap = metering.meter().tenants()
    for tenant, want in out["per_tenant_tokens"].items():
        assert snap[tenant]["tokens_out"] >= want


def test_router_load_report_tenants_and_quota_gauge():
    """Router.load_report() carries the per-tenant quota-utilization
    section and feeds the metering gauge."""
    from benchmarks.serve_load import build_tenant_session, make_adapters
    from tpudl.serve import Replica, Router

    adapters = make_adapters(2, rank=2, seed=0)
    session, _, _ = build_tenant_session(adapters, num_slots=2)
    names = sorted(adapters)
    router = Router(
        [Replica("r0", session)],
        tenant_classes={names[0]: {"max_inflight_tokens": 64}},
    )
    try:
        rep = router.load_report()
        assert names[0] in rep["tenants"]
        t = rep["tenants"][names[0]]
        assert t["quota_tokens"] == 64
        assert t["inflight_tokens"] == 0
        assert t["quota_utilization"] == 0.0
    finally:
        router.close()
    snap = metering.meter().tenants()
    assert snap[names[0]]["quota_utilization"] == 0.0
    text = metering.render_tenants()
    assert (
        f'serve_tenant_quota_utilization{{tenant="{names[0]}"}} 0'
        in text
    )
