"""WordPiece tokenizer (tpudl.data.tokenizer): HF parity + the raw-text
-> ids -> fine-tune vertical.

Parity discipline follows the model-weight imports: a
transformers.BertTokenizer built OFFLINE from the same vocab file must
produce identical ids/masks (no downloads — zero-egress environment)."""

import numpy as np
import pytest

from tpudl.data.tokenizer import (
    CLS,
    PAD,
    SEP,
    UNK,
    WordPieceTokenizer,
    basic_tokenize,
    build_wordpiece_vocab,
)

CORPUS = [
    "A wonderful, heartfelt film — truly moving!",
    "the plot was dreadful and the acting hollow.",
    "Quite charming; superb direction, dazzling camera work.",
    "boring... just boring. tedious pacing, bland script.",
    "An engaging story about a warm friendship.",
    "Café naïve résumé coöperate!",  # accents must strip
    "unbelievable unbelievably believable",
    "it's a don't-miss movie (really).",
]


@pytest.fixture(scope="module")
def tok():
    return WordPieceTokenizer(build_wordpiece_vocab(CORPUS, 2048))


def test_basic_tokenize_rules():
    assert basic_tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    assert basic_tokenize("café") == ["cafe"]  # accent stripped
    assert basic_tokenize("don't") == ["don", "'", "t"]
    assert basic_tokenize("  spaced\tout\n") == ["spaced", "out"]
    assert basic_tokenize("漢字ab") == ["漢", "字", "ab"]  # CJK chars split


def test_vocab_has_specials_first(tok):
    assert tok.vocab[PAD] == 0
    assert {UNK, CLS, SEP} <= set(tok.vocab)


def test_roundtrip_known_words(tok):
    for text in CORPUS:
        pieces = tok.tokenize(text)
        assert UNK not in pieces, (text, pieces)
        # de-wordpiece reassembles the basic-tokenized text
        rebuilt = "".join(p[2:] if p.startswith("##") else " " + p
                          for p in pieces).split()
        assert rebuilt == basic_tokenize(text)


def test_encode_shape_and_truncation(tok):
    ids, mask = tok.encode("a wonderful film", max_len=8)
    assert len(ids) == len(mask) == 8
    assert ids[0] == tok.cls_id and tok.sep_id in ids
    assert mask[: ids.index(tok.pad_id) if tok.pad_id in ids else 8] == [1] * (
        ids.index(tok.pad_id) if tok.pad_id in ids else 8
    )
    long_ids, long_mask = tok.encode(" ".join(["word"] * 100), max_len=16)
    assert len(long_ids) == 16 and long_ids[-1] == tok.sep_id
    assert sum(long_mask) == 16


def test_batch_call(tok):
    enc = tok(["great movie", "dreadful film, truly tedious"], max_len=12)
    assert enc["input_ids"].shape == (2, 12)
    assert enc["attention_mask"].dtype == np.int32


def test_hf_parity_same_vocab_file(tok, tmp_path):
    """Byte-parity with transformers.BertTokenizer over our vocab file:
    ids AND attention masks identical across punctuation, accents,
    unknowns, truncation, and padding."""
    transformers = pytest.importorskip("transformers")
    vocab_path = tmp_path / "vocab.txt"
    tok.save_vocab(str(vocab_path))
    hf = transformers.BertTokenizer(
        str(vocab_path), do_lower_case=True, local_files_only=True
    )
    texts = CORPUS + [
        "completely-unseen zxqv tokens!!",
        "MiXeD CaSe And   WEIRD   spacing",
        "truncate " + "very " * 60 + "long",
    ]
    for text in texts:
        ours_ids, ours_mask = tok.encode(text, max_len=32)
        hf_enc = hf(
            text, max_length=32, truncation=True, padding="max_length"
        )
        assert ours_ids == hf_enc["input_ids"], text
        assert ours_mask == hf_enc["attention_mask"], text


def test_vocab_file_roundtrip(tok, tmp_path):
    path = tmp_path / "vocab.txt"
    tok.save_vocab(str(path))
    tok2 = WordPieceTokenizer.from_vocab_file(str(path))
    assert tok2.vocab == tok.vocab


def test_text_dataset_to_ids_to_training(tmp_path):
    """The full vertical: raw-text Parquet -> trained vocab -> ids Parquet
    -> BERT fine-tune; loss decreases (the text signal is learnable)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.data.datasets import (
        materialize_sst2_text,
        normalize_sst2_batch,
        tokenize_text_dataset,
    )
    from tpudl.models.bert import BERT_TINY, BertForSequenceClassification
    from tpudl.train import create_train_state, make_classification_train_step

    text_dir = str(tmp_path / "text")
    ids_dir = str(tmp_path / "ids")
    text_conv = materialize_sst2_text(text_dir, num_rows=512)
    corpus = [
        str(s)
        for b in text_conv.make_batch_iterator(
            128, epochs=1, shuffle=False, drop_last=False
        )
        for s in b["sentence"]
    ]
    tok = WordPieceTokenizer(build_wordpiece_vocab(corpus, 1024))
    conv = tokenize_text_dataset(text_dir, ids_dir, tok, seq_len=32)

    model = BertForSequenceClassification(
        BERT_TINY(vocab_size=1024, num_heads=2, dtype=jnp.float32,
                  max_position_embeddings=64)
    )
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 32), jnp.int32),
        optax.adamw(3e-3),
    )
    step = jax.jit(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        )
    )
    rng = jax.random.key(1)
    first = last = None
    for i, batch in enumerate(
        conv.make_batch_iterator(64, epochs=None, shuffle=True)
    ):
        if i >= 40:
            break
        state, metrics = step(state, normalize_sst2_batch(batch), rng)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert last < first * 0.8, (first, last)
