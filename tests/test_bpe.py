"""Byte-level BPE: trainer invariants + byte-parity with
transformers.GPT2Tokenizer over the same vocab.json/merges.txt (the same
strategy as the WordPiece-vs-BertTokenizer parity tests — a locally
constructed reference tokenizer, zero egress)."""

import numpy as np
import pytest

from tpudl.data.bpe import (
    EOT_TOKEN,
    PAD_TOKEN,
    ByteBPETokenizer,
    bytes_to_unicode,
    train_bpe,
)

CORPUS = [
    "the movie was wonderful and the acting was wonderful too",
    "a dull and lifeless film , utterly forgettable",
    "it's a charming journey with heartfelt moments",
    "the plot was dull but the ending was charming",
    "don't watch this dreadful mess of a movie",
    "truly a wonderful story , wonderfully told",
    "unicode test: naïve café — 日本語 and emoji 🎬 survive bytes",
] * 3


def test_bytes_to_unicode_reversible():
    m = bytes_to_unicode()
    assert len(m) == 256
    assert len(set(m.values())) == 256  # bijective


def test_train_encode_decode_roundtrip():
    tok = train_bpe(CORPUS, vocab_size=512)
    assert tok.vocab[PAD_TOKEN] == 0
    assert len(tok.vocab) <= 512
    for text in CORPUS[:7]:
        ids = tok.encode_text(text)
        assert tok.decode(ids) == text  # byte-level: lossless, any input
    # merges actually learned: frequent words compress below char count
    assert len(tok.encode_text("wonderful")) < len("wonderful")


def test_encode_batch_contract():
    tok = train_bpe(CORPUS, vocab_size=512)
    batch = tok(["the movie was wonderful", "dull film"], max_len=16)
    assert batch["input_ids"].shape == (2, 16)
    assert batch["input_ids"].dtype == np.int32
    assert batch["input_ids"][0, 0] == tok.bos_id
    # mask marks exactly the non-pad prefix
    lens = batch["attention_mask"].sum(axis=1)
    for row, n in zip(batch["input_ids"], lens):
        assert (row[n:] == tok.pad_id).all()
        assert (row[:n] != tok.pad_id).all()


def test_truncation():
    tok = train_bpe(CORPUS, vocab_size=512)
    ids, mask = tok.encode("the movie was wonderful and charming", max_len=4)
    assert len(ids) == 4 and sum(mask) == 4


def test_file_roundtrip(tmp_path):
    tok = train_bpe(CORPUS, vocab_size=512)
    tok.save(str(tmp_path))
    tok2 = ByteBPETokenizer.from_files(
        str(tmp_path / "vocab.json"), str(tmp_path / "merges.txt")
    )
    for text in CORPUS[:7]:
        assert tok.encode_text(text) == tok2.encode_text(text)


def test_gpt2_tokenizer_parity(tmp_path):
    """Our encoder byte-matches transformers.GPT2Tokenizer over the SAME
    trained vocab/merges files — so real pretrained pairs drop in."""
    transformers = pytest.importorskip("transformers")

    tok = train_bpe(CORPUS, vocab_size=768)
    vocab_path, merges_path = tok.save(str(tmp_path))
    hf = transformers.GPT2Tokenizer(
        vocab_path, merges_path,
        unk_token=EOT_TOKEN, bos_token=EOT_TOKEN, eos_token=EOT_TOKEN,
    )
    cases = CORPUS[:7] + [
        "Unseen Words With Capitals!",
        "  leading and trailing spaces  ",
        "numbers 12345 and punct ?!...",
        "brand-new-hyphenated-compound",
    ]
    for text in cases:
        ours = tok.encode_text(text)
        theirs = hf.convert_tokens_to_ids(hf.tokenize(text))
        assert ours == theirs, (text, ours, theirs)


def test_tokenize_text_dataset_accepts_bpe(tmp_path):
    """The Parquet text->ids pipeline takes the BPE tokenizer through the
    same seam as WordPiece (the tokenizer __call__ contract)."""
    from tpudl.data.datasets import materialize_sst2_text, tokenize_text_dataset

    materialize_sst2_text(str(tmp_path / "text"), num_rows=256)
    tok = train_bpe(CORPUS, vocab_size=512)
    conv = tokenize_text_dataset(
        str(tmp_path / "text"), str(tmp_path / "ids"), tok, seq_len=32
    )
    b = next(conv.make_batch_iterator(32, shuffle=False, shard_index=0,
                                      num_shards=1))
    assert b["input_ids"].shape == (32, 32)
    assert (b["input_ids"][:, 0] == tok.bos_id).all()
