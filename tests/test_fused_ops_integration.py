"""Fused-epilogue tier wired through the models and the train loop.

``fused_ops="force"`` runs the actual Pallas kernels (interpret mode on
CPU) inside real models and real compiled train steps; ``fused_ops=True``
("auto") must fall back to the bit-identical composite off-TPU — the
dispatch-seam contract models rely on for the default path staying
unchanged."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.train.loop import (
    compile_step,
    create_train_state,
    cross_entropy_loss,
    make_classification_eval_step,
    make_classification_train_step,
)


def _bert_state(fused_ops, seed=0, dtype=jnp.float32):
    from tpudl.models.bert import BertConfig, BertForSequenceClassification

    cfg = BertConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, hidden_dropout=0.0, attention_dropout=0.0,
        max_position_embeddings=32, dtype=dtype, fused_ops=fused_ops,
    )
    model = BertForSequenceClassification(cfg)
    return create_train_state(
        jax.random.key(seed), model, jnp.zeros((1, 16), jnp.int32),
        optax.adamw(1e-3),
    )


def _batch(batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, 128, (batch, seq)).astype(np.int32),
        "attention_mask": np.ones((batch, seq), np.int32),
        "label": rng.integers(0, 2, (batch,)).astype(np.int32),
    }


def _step_fn(loss_impl="reference"):
    return make_classification_train_step(
        input_keys=("input_ids", "attention_mask"), label_key="label",
        loss_impl=loss_impl,
    )


def test_bert_fused_block_loss_and_grads_match_composite():
    """The full fused BERT block (fused LayerNorm+residual, fused
    bias+GeLU, fused cross-entropy) on a real
    make_classification_train_step: loss and updated params match the
    composite step within bf16-level tolerance."""
    mesh = make_mesh(MeshSpec(dp=-1))
    batch = _batch()
    rng = jax.random.key(1)

    results = {}
    for mode, loss_impl in ((False, "reference"), ("force", "fused")):
        state = _bert_state(mode)
        step = compile_step(
            _step_fn(loss_impl), mesh, state, None, donate_state=False
        )
        new_state, metrics = step(state, batch, rng)
        results[mode] = (new_state, metrics)

    (s_ref, m_ref), (s_fused, m_fused) = results[False], results["force"]
    np.testing.assert_allclose(
        float(m_fused["loss"]), float(m_ref["loss"]), rtol=1e-4, atol=1e-5
    )
    flat_ref = jax.tree_util.tree_leaves_with_path(s_ref.params)
    flat_fused = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(s_fused.params)
    )
    assert set(flat_fused) == set(
        jax.tree_util.keystr(p) for p, _ in flat_ref
    )
    for path, ref_leaf in flat_ref:
        key = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(flat_fused[key]), np.asarray(ref_leaf),
            rtol=2e-3, atol=2e-5, err_msg=f"param {key} diverged",
        )


def test_bert_fused_auto_is_reference_off_tpu():
    """fused_ops=True (auto) off-TPU must be the composite: the forward
    (loss) is BIT-identical, and the updated params agree to float
    reassociation level (autodiff walks a structurally different —
    mathematically identical — graph, the caveat class
    test_fused_dispatch documents for conv/dropout models)."""
    mesh = make_mesh(MeshSpec(dp=-1))
    batch = _batch()
    rng = jax.random.key(1)
    outs = []
    for mode in (False, True):
        state = _bert_state(mode)
        step = compile_step(
            _step_fn(), mesh, state, None, donate_state=False
        )
        new_state, metrics = step(state, batch, rng)
        outs.append((new_state, metrics))
    assert float(outs[0][1]["loss"]) == float(outs[1][1]["loss"])
    for a, b in zip(
        jax.tree.leaves(outs[0][0].params), jax.tree.leaves(outs[1][0].params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_donation_audit_with_fused_kernels():
    """The donation contract survives the fused tier: every old state
    leaf is deleted and >= 80% of buffers are reused in place when the
    step runs the Pallas kernels (test_fused_dispatch's audit, fused)."""
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state("force")
    step = compile_step(_step_fn("fused"), mesh, state, None)
    state = jax.device_put(state, step.state_shardings)
    batch = _batch()
    rng = jax.random.key(1)

    def ptrs(tree):
        out = set()
        for leaf in jax.tree.leaves(tree):
            for shard in leaf.addressable_shards:
                out.add(shard.data.unsafe_buffer_pointer())
        return out

    old_leaves = jax.tree.leaves(state)
    old_ptrs = ptrs(state)
    state2, _ = step(state, batch, rng)
    assert all(leaf.is_deleted() for leaf in old_leaves)
    reused = ptrs(state2) & old_ptrs
    assert len(reused) >= 0.8 * len(old_ptrs), (
        f"only {len(reused)}/{len(old_ptrs)} donated buffers reused with "
        "fused kernels enabled — a kernel boundary is silently copying"
    )


def test_bert_fused_eval_step_and_loss_impl():
    """Eval path: the fused per-example loss feeds the same masked-mean
    metrics as the composite."""
    mesh = make_mesh(MeshSpec(dp=-1))
    state = _bert_state(False)
    batch = _batch()
    ref_step = compile_step(
        make_classification_eval_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh, state, None, has_rng=False,
    )
    fused_step = compile_step(
        make_classification_eval_step(
            input_keys=("input_ids", "attention_mask"), label_key="label",
            loss_impl="fused",
        ),
        mesh, state, None, has_rng=False,
    )
    m_ref = ref_step(state, batch)
    m_fused = fused_step(state, batch)
    np.testing.assert_allclose(
        float(m_fused["loss"]), float(m_ref["loss"]), rtol=1e-5, atol=1e-6
    )
    assert float(m_fused["accuracy"]) == float(m_ref["accuracy"])


def test_cross_entropy_loss_impl_seam():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(13, 5)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 5, size=(13,)), jnp.int32)
    ref = cross_entropy_loss(logits, labels, 0.1)
    fused = cross_entropy_loss(logits, labels, 0.1, impl="fused")
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-5,
                               atol=1e-6)


def test_llama_fused_forward_and_grads():
    """Fused RMSNorm(+residual) and SwiGLU through the tiny Llama stack
    (the serve decode path's per-step ops): logits and grads match the
    composite."""
    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, 512, (2, 16)), jnp.int32
    )
    ref_model = LlamaForCausalLM(LLAMA_TINY(dtype=jnp.float32))
    fused_model = LlamaForCausalLM(
        LLAMA_TINY(dtype=jnp.float32, fused_ops="force")
    )
    variables = ref_model.init(jax.random.key(0), ids)

    z_ref = ref_model.apply(variables, ids)
    z_fused = fused_model.apply(variables, ids)
    np.testing.assert_allclose(
        np.asarray(z_fused), np.asarray(z_ref), rtol=1e-4, atol=1e-4
    )

    def loss(model):
        def f(params):
            z = model.apply({"params": params}, ids)
            return jnp.mean(z * z)
        return f

    g_ref = jax.grad(loss(ref_model))(variables["params"])
    g_fused = jax.grad(loss(fused_model))(variables["params"])
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(g_fused),
        jax.tree.leaves(g_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5,
            err_msg=f"grad {jax.tree_util.keystr(path)} diverged",
        )


def test_llama_fused_auto_is_bitwise_reference_off_tpu():
    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, 512, (2, 12)), jnp.int32
    )
    ref_model = LlamaForCausalLM(LLAMA_TINY(dtype=jnp.float32))
    auto_model = LlamaForCausalLM(
        LLAMA_TINY(dtype=jnp.float32, fused_ops=True)
    )
    variables = ref_model.init(jax.random.key(0), ids)
    z_ref = np.asarray(ref_model.apply(variables, ids))
    z_auto = np.asarray(auto_model.apply(variables, ids))
    assert (z_ref == z_auto).all()


@pytest.mark.tpu
def test_fused_kernels_compile_on_tpu():
    """Compiled (non-interpret) Pallas lowering sanity on real hardware
    — the CPU tier covers numerics in interpret mode; this covers the
    Mosaic compile path. Auto-skipped off-TPU by conftest."""
    from tpudl.ops.cross_entropy import (
        softmax_cross_entropy,
        softmax_cross_entropy_ref,
    )
    from tpudl.ops.mlp_fused import bias_gelu, bias_gelu_ref
    from tpudl.ops.norms import layer_norm, layer_norm_ref

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 768)), jnp.bfloat16)
    r = jnp.asarray(rng.normal(size=(64, 768)), jnp.bfloat16)
    scale = jnp.ones((768,))
    bias = jnp.zeros((768,))
    y, s = layer_norm(x, scale, bias, r, impl="fused", interpret=False)
    yr, _ = layer_norm_ref(x, scale, bias, r)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0.05, atol=0.05,
    )
    np.testing.assert_allclose(
        np.asarray(
            bias_gelu(x, bias, impl="fused", interpret=False), np.float32
        ),
        np.asarray(bias_gelu_ref(x, bias), np.float32),
        rtol=0.05, atol=0.02,
    )
    logits = jnp.asarray(rng.normal(size=(32, 1000)) * 3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, 1000, size=(32,)), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(
            softmax_cross_entropy(
                logits, labels, impl="fused", interpret=False
            )
        ),
        np.asarray(softmax_cross_entropy_ref(logits, labels)),
        rtol=1e-4, atol=1e-4,
    )


def test_bert_param_tree_identical_across_modes():
    """Checkpoints/HF imports interchange between fused and composite:
    identical param paths, shapes, dtypes."""
    ids = jnp.zeros((1, 16), jnp.int32)
    trees = {}
    for mode in (False, "force"):
        from tpudl.models.bert import (
            BertConfig,
            BertForSequenceClassification,
        )

        cfg = BertConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
            intermediate_size=64, max_position_embeddings=32,
            dtype=jnp.float32, fused_ops=mode,
        )
        variables = BertForSequenceClassification(cfg).init(
            jax.random.key(0), ids
        )
        trees[mode] = {
            jax.tree_util.keystr(p): (l.shape, l.dtype)
            for p, l in jax.tree_util.tree_leaves_with_path(
                variables["params"]
            )
        }
    assert trees[False] == trees["force"]


def test_block_size_overrides_preserve_parity():
    """The --sweep-blocks knobs (norms.BLOCK_ROWS_OVERRIDE /
    cross_entropy.VOCAB_BLOCK_OVERRIDE) change only the kernel grid:
    fused outputs at a non-default block size still match the
    composite references (interpret mode on CPU)."""
    from tpudl.ops import cross_entropy as ce_mod
    from tpudl.ops import norms as norms_mod
    from tpudl.ops.cross_entropy import (
        softmax_cross_entropy,
        softmax_cross_entropy_ref,
    )
    from tpudl.ops.norms import layer_norm, layer_norm_ref

    x = jax.random.normal(jax.random.key(0), (48, 96), jnp.float32)
    scale = jnp.ones((96,))
    bias = jnp.full((96,), 0.1)
    logits = jax.random.normal(jax.random.key(1), (24, 384), jnp.float32)
    labels = jax.random.randint(jax.random.key(2), (24,), 0, 384)
    try:
        norms_mod.BLOCK_ROWS_OVERRIDE = 32
        ce_mod.VOCAB_BLOCK_OVERRIDE = 128
        np.testing.assert_allclose(
            layer_norm(x, scale, bias, impl="fused"),
            layer_norm_ref(x, scale, bias),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            softmax_cross_entropy(logits, labels, impl="fused"),
            softmax_cross_entropy_ref(logits, labels),
            rtol=1e-5, atol=1e-5,
        )
    finally:
        norms_mod.BLOCK_ROWS_OVERRIDE = None
        ce_mod.VOCAB_BLOCK_OVERRIDE = None
    try:
        norms_mod.BLOCK_ROWS_OVERRIDE = 0
        with pytest.raises(ValueError, match="block-rows"):
            layer_norm(x, scale, bias, impl="fused")
    finally:
        norms_mod.BLOCK_ROWS_OVERRIDE = None


def test_fused_epilogue_sweep_blocks_smoke():
    """benchmarks/fused_epilogue.py --sweep-blocks finds a best block
    per family at smoke shapes (CPU interpret mode) and restores the
    heuristic (override None) afterwards."""
    from benchmarks.fused_epilogue import main as bench_main
    from tpudl.ops import cross_entropy as ce_mod
    from tpudl.ops import norms as norms_mod

    bench_main(["--sweep-blocks", "--smoke"])
    assert norms_mod.BLOCK_ROWS_OVERRIDE is None
    assert ce_mod.VOCAB_BLOCK_OVERRIDE is None
