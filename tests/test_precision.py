"""Mixed-precision training tier (tpudl.train.precision +
tpudl.ops.fp8_dot) — ISSUE 15 / ROADMAP item 6's training half.

Five contracts: (1) IDENTITY — the f32 policy is bitwise the legacy
no-policy step, and policy=None stays untouched; (2) PARITY — bf16 and
fp8 fixed-seed runs hold their documented loss bands against the f32
control while master weights stay f32; (3) DYNAMICS — dynamic loss
scaling grows/backs off exactly, a nonfinite gradient SKIPS the step
(params/opt/step/rings bitwise untouched) inside the SAME compiled
program, fp8 amax rings advance with observed forward/gradient amaxes,
saturation clips instead of NaNing, and moving scales never recompile
(RecompileWatcher audit); (4) RESUME — both checkpoint managers
round-trip the whole precision state (loss-scale schedule + amax
windows) and a mid-run restore replays the uninterrupted run bitwise;
(5) SEAMS — rule-selected moment dtypes are bitwise optax's mu_dtype,
and every invalid policy/state/config combination raises by name.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.models.bert import BertConfig, BertForSequenceClassification
# tpudl.ops re-exports the fp8_dot FUNCTION, shadowing the submodule
# name in the package namespace (the flash_attention precedent) —
# resolve the MODULE explicitly.
import importlib

fp8_mod = importlib.import_module("tpudl.ops.fp8_dot")
from tpudl.runtime import MeshSpec, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    make_classification_eval_step,
    make_classification_train_step,
)
from tpudl.train import precision as precision_mod
from tpudl.train.precision import LossScaleConfig

SEQ = 8
BATCH = 8  # divisible by the CPU host's 8 virtual devices (dp=-1)
STEPS = 6

_CFG = dict(
    vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
    intermediate_size=64, max_position_embeddings=16, num_labels=2,
    dtype=jnp.float32, hidden_dropout=0.0, attention_dropout=0.0,
)

#: The benchmark's documented bands (benchmarks/train_precision.py).
BF16_BAND = 0.03
FP8_BAND = 0.08


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshSpec(dp=-1))


@pytest.fixture(scope="module")
def batches():
    rng = np.random.default_rng(7)
    return [
        {
            "input_ids": jnp.asarray(
                rng.integers(1, 64, (BATCH, SEQ)), jnp.int32
            ),
            "attention_mask": jnp.ones((BATCH, SEQ), jnp.int32),
            "label": jnp.asarray(rng.integers(0, 2, (BATCH,)), jnp.int32),
        }
        for _ in range(STEPS)
    ]


def _build(mesh, precision, fp8_train=False):
    cfg = BertConfig(**_CFG, fp8_train="force" if fp8_train else False)
    if precision is not None:
        # Compute dtype rides the model's dtype seam (configure_model)
        # — the bf16/fp8 cells really compute in bf16, which
        # test_bf16_matmuls_actually_run_bf16 pins via jaxpr.
        cfg = precision_mod.resolve_policy(precision).configure_model(cfg)
    model = BertForSequenceClassification(cfg)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, SEQ), jnp.int32),
        optax.adamw(1e-3), precision=precision,
    )
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"),
            label_key="label", precision=precision,
        ),
        mesh, state, None, precision=precision,
    )
    return model, state, step


def _drive(step, state, batches, rng=None):
    rng = jax.random.key(1) if rng is None else rng
    losses, metrics = [], None
    for batch in batches:
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    return state, losses, metrics


def _fork(state):
    """Deep copy of a TrainState's buffers — the compiled train step
    DONATES its state argument, so anything a later test reads (or
    re-drives) must step a copy, never a shared fixture state."""
    return jax.tree.map(jnp.copy, state)


_RUNS = {}


@pytest.fixture(scope="module")
def runs(mesh, batches):
    """One fixed-seed run per cell, compiled once and shared by every
    test in the module (1-vCPU budget: compiles dominate). ``state0``
    is the pristine init (the drive consumed a fork of it)."""
    if not _RUNS:
        for name, (prec, fp8) in {
            "legacy": (None, False),
            "f32": ("f32", False),
            "bf16": ("bf16", False),
            "fp8": ("fp8", True),
        }.items():
            model, state0, step = _build(mesh, prec, fp8_train=fp8)
            state, losses, metrics = _drive(step, _fork(state0), batches)
            _RUNS[name] = {
                "model": model, "state0": state0, "step": step,
                "state": state, "losses": losses, "metrics": metrics,
            }
    return _RUNS


# ---------------------------------------------------------------------------
# 1. Identity + parity
# ---------------------------------------------------------------------------


def test_f32_policy_bitwise_identical_to_legacy(runs):
    """policy("f32") is the identity: same losses, same final params,
    bit for bit — the control arm costs nothing."""
    assert runs["legacy"]["losses"] == runs["f32"]["losses"]
    for a, b in zip(
        jax.tree.leaves(runs["legacy"]["state"].params),
        jax.tree.leaves(runs["f32"]["state"].params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_parity_band_and_f32_masters(runs):
    diff = abs(runs["bf16"]["losses"][-1] - runs["legacy"]["losses"][-1])
    assert diff <= BF16_BAND, diff
    # Master weights never leave f32 — the policy casts inside the
    # loss function only.
    assert all(
        leaf.dtype == jnp.float32
        for leaf in jax.tree.leaves(runs["bf16"]["state"].params)
    )
    # And the cast actually happened: bf16 arithmetic diverges from
    # the control at SOME step (fixed seed — divergence IS precision).
    assert any(
        a != b
        for a, b in zip(runs["bf16"]["losses"], runs["legacy"]["losses"])
    )


def test_fp8_parity_band_and_ring_advance(runs):
    diff = abs(runs["fp8"]["losses"][-1] - runs["legacy"]["losses"][-1])
    assert diff <= FP8_BAND, diff
    metrics = runs["fp8"]["metrics"]
    assert float(metrics["loss_scale"]) == 2.0**15
    assert float(metrics["grad_skipped"]) == 0.0
    prec = runs["fp8"]["state"].precision
    assert int(np.asarray(prec["loss_scale"]["skipped"])) == 0
    # Every site's rings advanced with real (positive) amaxes in all
    # three tensor classes.
    flat = {
        jax.tree_util.keystr(p): np.asarray(v)
        for p, v in jax.tree_util.tree_flatten_with_path(prec["fp8"])[0]
    }
    for kind in ("x_hist", "w_hist", "g_hist"):
        hists = [v for k, v in flat.items() if kind in k]
        assert hists
        assert all(h[: STEPS].min() > 0 for h in hists), kind


def _dot_operand_dtypes(closed_jaxpr):
    """Dtypes of every dot_general's operands, walking call/closed
    sub-jaxprs — the compute-precision ground truth."""
    out = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dot_general":
                out.extend(v.aval.dtype for v in eqn.invars)
            for param in eqn.params.values():
                if hasattr(param, "jaxpr"):
                    walk(param.jaxpr)
                elif hasattr(param, "eqns"):
                    walk(param)

    walk(closed_jaxpr.jaxpr)
    return out


def test_bf16_matmuls_actually_run_bf16(runs, batches):
    """The compute dtype must LAND: a flax module re-promotes params
    to its own dtype, so only the configure_model seam moves the
    matmul precision — this pins the traced dot operands so a policy
    whose compute dtype silently stops taking effect (the rounded-f32
    failure mode) breaks loudly."""
    ids, mask = batches[0]["input_ids"], batches[0]["attention_mask"]

    def trace(run):
        model, params = run["model"], run["state0"].params
        return jax.make_jaxpr(
            lambda p: model.apply({"params": p}, ids, mask, train=False)
        )(params)

    bf16_dots = _dot_operand_dtypes(trace(runs["bf16"]))
    f32_dots = _dot_operand_dtypes(trace(runs["legacy"]))
    assert bf16_dots and f32_dots
    # Every encoder/pooler matmul runs bf16; the only f32 dot allowed
    # is the CLASSIFIER head (no dtype seam by design — the same
    # full-precision keep class the quantizer names).
    n_f32 = sum(1 for d in bf16_dots if d == jnp.float32)
    assert n_f32 <= 2, bf16_dots  # one head dot = two operands
    assert sum(1 for d in bf16_dots if d == jnp.bfloat16) >= 10
    assert all(d == jnp.float32 for d in f32_dots), set(f32_dots)


def test_cast_params_rule_classes(runs):
    """bf16 cast rules: kernels/embeddings go bf16, norm scales and
    biases stay f32 — the same keep taxonomy as the quantizer."""
    pol = precision_mod.policy("bf16")
    casted = pol.cast_params(runs["legacy"]["state0"].params)
    flat = jax.tree_util.tree_flatten_with_path(casted)[0]
    n_bf16 = n_f32 = 0
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        if name.endswith("['kernel']") or name.endswith("['embedding']"):
            assert leaf.dtype == jnp.bfloat16, name
            n_bf16 += 1
        else:
            assert leaf.dtype == jnp.float32, name
            n_f32 += 1
    assert n_bf16 > 10 and n_f32 > 10


# ---------------------------------------------------------------------------
# 2. Loss-scale dynamics + skip semantics
# ---------------------------------------------------------------------------


def test_loss_scale_transitions_unit():
    cfg = LossScaleConfig(
        init=4.0, growth_factor=2.0, backoff_factor=0.5,
        growth_interval=3, max_scale=16.0, min_scale=1.0,
    )
    ls = {
        "scale": jnp.float32(4.0),
        "growth_count": jnp.int32(0),
        "skipped": jnp.int32(0),
    }
    ok = jnp.asarray(True)
    for expect_scale, expect_count in [(4, 1), (4, 2), (8, 0), (8, 1)]:
        ls = precision_mod.update_loss_scale(ls, cfg, ok)
        assert float(ls["scale"]) == expect_scale
        assert int(ls["growth_count"]) == expect_count
    # Backoff resets the streak and floors at min_scale.
    bad = jnp.asarray(False)
    for expect_scale in (4.0, 2.0, 1.0, 1.0):
        ls = precision_mod.update_loss_scale(ls, cfg, bad)
        assert float(ls["scale"]) == expect_scale
        assert int(ls["growth_count"]) == 0
    assert int(ls["skipped"]) == 4
    # Growth caps at max_scale.
    ls = {
        "scale": jnp.float32(16.0),
        "growth_count": jnp.int32(2),
        "skipped": jnp.int32(0),
    }
    ls = precision_mod.update_loss_scale(ls, cfg, ok)
    assert float(ls["scale"]) == 16.0


def test_nonfinite_grad_skips_step_in_same_program(runs, batches):
    """Poison one weight to inf so the backward goes nonfinite: the
    SAME compiled fp8 program must skip — params, opt state, step
    counter, and amax rings bitwise untouched; the loss scale backs
    off; the skipped counter advances. No recompile (values are data)."""
    from tpudl.analysis.dispatch import RecompileWatcher

    base = runs["fp8"]["state"]
    forked = _fork(base)
    marked = [False]

    def poison(leaf):
        if not marked[0] and jnp.ndim(leaf) == 2:
            marked[0] = True
            return leaf.at[0, 0].set(jnp.inf)
        return leaf

    poisoned = forked.replace(
        params=jax.tree.map(poison, forked.params)
    )
    assert marked[0]
    # Host snapshots BEFORE the step: donation deletes the inputs.
    params_before = jax.device_get(poisoned.params)
    rings_before = jax.device_get(poisoned.precision["fp8"])
    step_before = int(np.asarray(base.step))

    with RecompileWatcher() as watcher:
        new_state, metrics = runs["fp8"]["step"](
            poisoned, batches[0], jax.random.key(1)
        )
    assert watcher.count == 0
    assert float(metrics["grad_skipped"]) == 1.0
    assert int(np.asarray(new_state.step)) == step_before
    for a, b in zip(
        jax.tree.leaves(params_before),
        jax.tree.leaves(new_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(rings_before),
        jax.tree.leaves(new_state.precision["fp8"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ls = new_state.precision["loss_scale"]
    assert float(ls["scale"]) == 2.0**14  # backed off from 2^15
    assert int(ls["growth_count"]) == 0
    assert int(ls["skipped"]) == 1


def test_fp8_steady_state_never_recompiles(runs, batches):
    """Delayed scaling's whole point: amax windows and scales move as
    traced data, so steps after warmup compile NOTHING."""
    from tpudl.analysis.dispatch import assert_no_recompiles

    state = _fork(runs["fp8"]["state"])
    step = runs["fp8"]["step"]
    with assert_no_recompiles(label="fp8 train steady state"):
        for batch in batches[:3]:
            state, _ = step(state, batch, jax.random.key(1))


# ---------------------------------------------------------------------------
# 3. fp8 kernel units: saturation, ring hygiene
# ---------------------------------------------------------------------------


def test_fp8_saturation_clips_never_nans():
    """A step whose values outgrow the window's scale saturates (clip
    to the format max before the cast — a bare astype would NaN on
    e4m3) and reports the TRUE amax so the next scale covers it."""
    hist = fp8_mod.update_amax_history(
        fp8_mod.amax_history_init(4), jnp.float32(1.0)
    )  # window says amax 1.0 -> scale 1/448
    x = jnp.full((2, 4), 1000.0, jnp.float32)  # 448x past the window
    w = jnp.eye(4, dtype=jnp.float32)
    out, x_amax, _ = fp8_mod.fp8_dot(
        x, w, hist, hist, hist, jnp.zeros(()), impl="fused"
    )
    assert bool(jnp.all(jnp.isfinite(out)))
    assert float(x_amax) == 1000.0
    # Ring advance with the true amax widens next step's scale.
    new_hist = fp8_mod.update_amax_history(hist, x_amax)
    assert float(fp8_mod.history_scale(new_hist, fp8_mod.E4M3_MAX)) == (
        pytest.approx(1000.0 / 448.0)
    )


def test_amax_ring_rejects_nonfinite():
    hist = fp8_mod.update_amax_history(
        fp8_mod.amax_history_init(3), jnp.float32(5.0)
    )
    poisoned = fp8_mod.update_amax_history(hist, jnp.float32(np.inf))
    assert bool(jnp.all(jnp.isfinite(poisoned)))
    assert float(poisoned[0]) == 5.0  # window max, not the inf


def test_fp8_dot_grad_parity_and_probe():
    """Both impls agree with the f32 reference within the fp8 grid's
    tolerance, and the gradient amax rides out as g_probe's cotangent."""
    key = jax.random.key(3)
    x = jax.random.normal(key, (4, 8), jnp.float32) * 0.5
    w = jax.random.normal(jax.random.key(4), (8, 3), jnp.float32) * 0.1
    hist = fp8_mod.amax_history_init(4)

    gref = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2), argnums=(0, 1))(
        x, w
    )
    for impl in ("fused", "reference"):

        def f(x, w, probe):
            out, _, _ = fp8_mod.fp8_dot(
                x, w, hist, hist, hist, probe, impl=impl
            )
            return jnp.sum(out**2)

        grads = jax.grad(f, argnums=(0, 1, 2))(x, w, jnp.zeros(()))
        np.testing.assert_allclose(grads[0], gref[0], atol=0.08)
        np.testing.assert_allclose(grads[1], gref[1], atol=0.08)
        assert float(grads[2]) > 0.0  # the amax ride-out


# ---------------------------------------------------------------------------
# 4. Checkpoint round-trip: schedule-identical resume (the PR-4 idiom)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("async_save", [False, True])
def test_precision_state_resumes_schedule_identical(
    runs, batches, tmp_path, async_save
):
    """Save mid-run, restore into a FRESH state, continue: the resumed
    trajectory is bitwise the uninterrupted one — which can only hold
    if the loss-scale schedule AND every amax window round-tripped."""
    from tpudl.checkpoint import CheckpointManager

    step = runs["fp8"]["step"]
    state0 = runs["fp8"]["state0"]
    rng = jax.random.key(1)

    # Uninterrupted control over the module's fixed batch stream.
    control_losses = runs["fp8"]["losses"]

    with CheckpointManager(
        str(tmp_path / f"ckpt_{async_save}"), async_save=async_save
    ) as mgr:
        state = _fork(state0)
        for batch in batches[:3]:
            state, _ = step(state, batch, rng)
        mgr.save(3, state)
        mgr.wait_until_finished()

        # Restore into a freshly-initialized state (different values,
        # same structure) — the resuming-program contract.
        _, fresh_state, _ = _build_cached_fresh(runs)
        restored = mgr.restore(fresh_state, 3)

    # The precision state round-tripped exactly.
    for a, b in zip(
        jax.tree.leaves(state.precision),
        jax.tree.leaves(restored.precision),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    resumed_losses = []
    for batch in batches[3:]:
        restored, metrics = step(restored, batch, rng)
        resumed_losses.append(float(metrics["loss"]))
    assert resumed_losses == control_losses[3:]


def _build_cached_fresh(runs):
    """A fresh fp8 TrainState (same structure as the module's run,
    different init values) without recompiling anything."""
    if "fresh" not in _RUNS:
        cfg = BertConfig(**_CFG, fp8_train="force")
        model = BertForSequenceClassification(cfg)
        state = create_train_state(
            jax.random.key(99), model, jnp.zeros((1, SEQ), jnp.int32),
            optax.adamw(1e-3), precision="fp8",
        )
        _RUNS["fresh"] = (model, state, None)
    model, state, _ = _RUNS["fresh"]
    return model, state, None


def test_state_payloads_carry_precision(runs):
    from tpudl.checkpoint import _state_payload
    from tpudl.ft.manager import state_payload

    state = runs["fp8"]["state"]
    for payload in (_state_payload(state), state_payload(state)):
        assert "precision" in payload
        assert "loss_scale" in payload["precision"]
        assert "fp8" in payload["precision"]
    # Legacy states serialize exactly as before — no new keys.
    legacy = runs["legacy"]["state"]
    for payload in (_state_payload(legacy), state_payload(legacy)):
        assert "precision" not in payload


# ---------------------------------------------------------------------------
# 5. Seams: moment rules, eval, validation errors
# ---------------------------------------------------------------------------


def test_moment_rules_bitwise_match_optax_mu_dtype():
    """apply_moment_rules is numerically optax's mu_dtype: same stored
    dtypes, same values, bit for bit — benchmarks/bert_mu_dtype.py's
    drift gate."""
    params = {
        "a": {"kernel": jnp.ones((4, 3)) * 0.1, "bias": jnp.zeros((3,))},
        "b": {"kernel": jnp.ones((3, 2)) * 0.2},
    }
    pol = precision_mod.policy("f32", bf16_moments=True)
    tx_policy = precision_mod.apply_moment_rules(
        optax.adamw(1e-2), pol
    )
    tx_optax = optax.adamw(1e-2, mu_dtype=jnp.bfloat16)
    s_pol, s_opt = tx_policy.init(params), tx_optax.init(params)
    grads = jax.tree.map(lambda p: p * 0.5 + 0.01, params)
    for _ in range(3):
        u_pol, s_pol = tx_policy.update(grads, s_pol, params)
        u_opt, s_opt = tx_optax.update(grads, s_opt, params)
    for a, b in zip(jax.tree.leaves(s_pol), jax.tree.leaves(s_opt)):
        assert jnp.asarray(a).dtype == jnp.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(u_pol), jax.tree.leaves(u_opt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # And the mu leaves actually store bf16.
    mus = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(s_pol)[0]
        if ".mu" in jax.tree_util.keystr(path)
    ]
    assert mus and all(m.dtype == jnp.bfloat16 for m in mus)


def test_eval_step_reads_fp8_state(runs, batches, mesh):
    eval_step = compile_step(
        make_classification_eval_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh, runs["fp8"]["state"], None, has_rng=False,
    )
    metrics = eval_step(runs["fp8"]["state"], batches[0])
    assert np.isfinite(float(metrics["loss"]))


def test_validation_errors(runs, mesh):
    # A policy that carries state must find it on the TrainState.
    with pytest.raises(ValueError, match="loss-scale state"):
        compile_step(
            make_classification_train_step(precision="fp8"),
            mesh, runs["legacy"]["state0"], None, precision="fp8",
        )
    # fp8 policy needs a model with fp8 sites.
    cfg = BertConfig(**_CFG)
    with pytest.raises(ValueError, match="fp8_train"):
        create_train_state(
            jax.random.key(0),
            BertForSequenceClassification(cfg),
            jnp.zeros((1, SEQ), jnp.int32),
            optax.adamw(1e-3),
            precision="fp8",
        )
    # fp8_train is exclusive with serving quantization.
    bad = BertConfig(**_CFG, fp8_train=True, weight_dtype="int8")
    with pytest.raises(ValueError, match="mutually exclusive"):
        BertForSequenceClassification(bad).init(
            jax.random.key(0), jnp.zeros((1, SEQ), jnp.int32)
        )
    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM

    with pytest.raises(ValueError, match="does not compose"):
        LlamaForCausalLM(
            LLAMA_TINY(fp8_train=True, weight_dtype="int8")
        ).init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="unknown precision policy"):
        precision_mod.policy("fp4")


def test_fp8_accumulation_parity_band(runs, mesh, batches):
    """fp8 x gradient accumulation (the lifted refusal): accum_steps=2
    over the same fixed-seed batches stays within the fp8 parity band
    of the monolithic fp8 run. Forward amax observations combine by
    max across microbatches (exactly the monolithic amax); the g ring
    sees the per-microbatch cotangent scale, so the comparison is a
    band, not bitwise. Each batch is self-concatenated to 2B rows so
    the accum split divides the mesh's 8 dp shards; duplicated rows
    leave the mean loss and gradient unchanged, so the monolithic
    B-row run stays the valid control."""
    doubled = [
        {k: jnp.concatenate([v, v]) for k, v in batch.items()}
        for batch in batches
    ]
    cfg = precision_mod.resolve_policy("fp8").configure_model(
        BertConfig(**_CFG, fp8_train="force")
    )
    model = BertForSequenceClassification(cfg)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, SEQ), jnp.int32),
        optax.adamw(1e-3), precision="fp8",
    )
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"),
            label_key="label", precision="fp8", accum_steps=2,
        ),
        mesh, state, None, precision="fp8",
    )
    _, losses, _ = _drive(step, state, doubled)
    diff = abs(losses[-1] - runs["fp8"]["losses"][-1])
    assert diff <= FP8_BAND, diff
    # The rings really advanced under accumulation (positive amaxes).
    assert all(np.isfinite(losses))


def test_fp8_lora_cell(mesh):
    """fp8_train x lora_rank (the opened cell): Fp8Dense carries the
    LoRADense adapter leaves, so one tree holds fp8 amax state AND
    extractable rank-r factors — the flywheel refresh's fp8 arm."""
    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
    from tpudl.models.lora import extract_adapters, lora_param_labels

    model = LlamaForCausalLM(LLAMA_TINY(fp8_train=True, lora_rank=2))
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )
    assert "fp8" in variables
    adapters = extract_adapters(variables["params"])
    assert adapters  # every projection site carries (lora_a, lora_b)
    for site in adapters.values():
        assert site["lora_a"].shape[-1] == 2
        np.testing.assert_array_equal(np.asarray(site["lora_b"]), 0.0)
    # The frozen-base optimizer split sees the same labels as LoRADense.
    labels = jax.tree.leaves(lora_param_labels(variables["params"]))
    assert "train" in labels and "freeze" in labels
    # Forward runs (zero-init B: fp8-base output, adapters contribute 0).
    logits = model.apply(
        {"params": variables["params"], "fp8": variables["fp8"]},
        jnp.zeros((1, 8), jnp.int32),
    )
    assert np.isfinite(np.asarray(logits)).all()
