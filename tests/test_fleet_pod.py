"""tpudl.fleet: pod-real mesh replicas, migration transport, elastic
reshard-restore, and the chip mover (ISSUE 19).

Correctness bars, all on the fake 8-device CPU host
(``--xla_force_host_platform_device_count=8``, tests/conftest.py):

- a Router over TWO pjit-sharded ``MeshReplica``s (disjoint 4-device
  tensor-parallel meshes) is token-for-token ``generate()`` — the
  placement contract does not know the mesh exists;
- a checkpoint written on a 4-device fsdp mesh reshard-restores
  BITWISE (params AND optimizer state) onto an 8-device mesh and back,
  and an uncovered leaf raises instead of silently replicating;
- a mid-stream request migrates across a real process boundary
  (socket transport into a separately-compiled survivor) with ZERO
  prefill dispatches on the target and an exact continuation;
- a speculating engine's migration payload carries the draft-cache
  remainder, so draft/target lens-lockstep survives failover — pinned
  by exact sampled-stream parity through the transport layer (a
  corrupted draft would change which proposals are made and therefore
  which uniforms are consumed);
- the chip mover's hysteresis tick moves devices training -> serving
  -> training with sustain windows and cooldown honored (fake clock;
  the end-to-end scenario with a real trainer and router runs in
  ``benchmarks.fleet_mesh`` / the ci_check fleet smoke stage).
"""

import dataclasses
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudl.fleet import (
    ChipMover,
    ChipMoverConfig,
    ElasticTrainer,
    FileChannel,
    MeshReplica,
    MigrationEndpoint,
    TransportError,
    build_mesh_session,
    deliver_to_session,
    migrate_request,
    recv_frame,
    reshard_restore,
    send_frame,
)
from tpudl.fleet.reshard import (
    ELASTIC_RESNET_RULES,
    cohort_mesh,
    elastic_shardings,
)
from tpudl.fleet.transport import FRAME_MAGIC, payload_request_id
from tpudl.ft.manager import AsyncCheckpointManager, state_payload
from tpudl.models.generate import generate, paged_decode_fn, prefill_fn
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.models.resnet import ResNetTiny
from tpudl.obs import registry
from tpudl.parallel.sharding import FSDP_RULES
from tpudl.runtime.mesh import MeshSpec
from tpudl.serve import MigrationCompatError, Request, Router, ServeSession
from tpudl.serve.cache import PagedKVCache
from tpudl.train import create_train_state, make_classification_train_step

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8
PAGE = 8


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def programs(model_and_params):
    """One compiled prefill/decode pair shared by every plain paged
    session below (the test_serve_chaos idiom — per-test sessions,
    module-wide compiles)."""
    model, params = model_and_params
    pf = jax.jit(prefill_fn(model))
    dec = jax.jit(paged_decode_fn(model, PAGE, False))
    ids = jax.ShapeDtypeStruct((2, PROMPT_LEN), jnp.int32)
    _, template = jax.eval_shape(prefill_fn(model), params, ids, ids)
    return {
        "model": model, "params": params, "prefill": pf,
        "decode": dec, "template": template,
    }


def _psession(programs, **kw):
    cache = PagedKVCache(programs["template"], page_size=PAGE)
    return ServeSession(
        programs["prefill"], programs["decode"], programs["params"],
        programs["template"], PROMPT_LEN, cache=cache, **kw,
    )


def _want(model, params, req):
    return np.asarray(
        generate(
            model, params, jnp.asarray(req.input_ids, jnp.int32)[None, :],
            max_new_tokens=req.max_new_tokens,
        )
    )[0]


def _greedy_requests(n, seed=0, max_new=10, tag="r"):
    rng = np.random.default_rng(seed)
    return [
        Request(
            f"{tag}{i}",
            rng.integers(
                1, CFG.vocab_size,
                size=int(rng.integers(2, PROMPT_LEN + 1)),
            ).tolist(),
            max_new_tokens=max_new,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# transport framing + spool (no model, no mesh)
# ---------------------------------------------------------------------------


def test_frame_roundtrip_and_clean_eof():
    a, b = socket.socketpair()
    try:
        payloads = [b"x" * 3, b"", b"y" * 1000]
        for p in payloads:
            send_frame(a, p)
        a.close()
        got = []
        while True:
            p = recv_frame(b)
            if p is None:
                break
            got.append(p)
        assert got == payloads
    finally:
        b.close()


def test_frame_bad_magic_and_truncation():
    a, b = socket.socketpair()
    try:
        a.sendall(b"NOTFRAME" + b"\x00" * 8)
        with pytest.raises(TransportError, match="magic"):
            recv_frame(b)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        # A frame that promises more bytes than the stream delivers.
        import struct

        a.sendall(FRAME_MAGIC + struct.pack("<Q", 100) + b"short")
        a.close()
        with pytest.raises(TransportError, match="truncated"):
            recv_frame(b)
    finally:
        b.close()


def test_frame_oversize_refused_before_allocation():
    import struct

    a, b = socket.socketpair()
    try:
        a.sendall(FRAME_MAGIC + struct.pack("<Q", 1 << 40))
        with pytest.raises(TransportError, match="exceeds"):
            recv_frame(b)
    finally:
        a.close()
        b.close()


def test_file_channel_spool_order_and_claims():
    with tempfile.TemporaryDirectory() as d:
        ch = FileChannel(d)
        names = [ch.put(p) for p in (b"first", b"second", b"third")]
        assert len(names) == len(set(names))
        # An uncommitted temp file must be invisible to take/drain.
        with open(os.path.join(d, "junk.tmp"), "wb") as f:
            f.write(b"garbage")
        assert len(ch) == 3
        assert ch.take() == b"first"
        assert ch.drain() == [b"second", b"third"]
        assert ch.take() is None
        assert len(ch) == 0


# ---------------------------------------------------------------------------
# chip mover hysteresis (fake trainer/router/clock — policy only)
# ---------------------------------------------------------------------------


class _FakeTrainer:
    def __init__(self, devices):
        self.devices = list(devices)
        self.grants = [list(devices)]
        self.restarts = 0
        self.preempts = 0

    def preempt(self, timeout_s=None):
        self.preempts += 1

    def restart(self, devices):
        self.devices = list(devices)
        self.grants.append(list(devices))
        self.restarts += 1
        return self


class _FakeRouter:
    def __init__(self):
        self.added = []
        self.removed = []

    def add_replica(self, replica):
        self.added.append(replica)

    def remove_replica(self, name, drain=False):
        self.removed.append((name, drain))


def test_chipmover_hysteresis_cooldown_and_split():
    devices = [f"d{i}" for i in range(8)]
    trainer = _FakeTrainer(devices)
    router = _FakeRouter()
    burn = {"on": False}
    now = {"t": 0.0}
    spawned = []

    def spawn(name, devs):
        spawned.append((name, list(devs)))
        return (name, tuple(devs))

    mover = ChipMover(
        router, trainer, spawn,
        ChipMoverConfig(burn_sustain_s=1.0, clear_sustain_s=2.0,
                        cooldown_s=5.0, serve_share=0.5),
        clock=lambda: now["t"], burn_fn=lambda: burn["on"],
    )
    assert mover.evaluate() is None  # idle, no burn
    burn["on"] = True
    assert mover.evaluate() is None  # burn starts the sustain window
    now["t"] = 0.5
    assert mover.evaluate() is None  # not sustained yet
    now["t"] = 1.0
    assert mover.evaluate() == "to_serving"
    assert mover.state == "borrowed"
    assert trainer.preempts == 1 and trainer.restarts == 1
    assert trainer.devices == devices[:4]  # training kept the head
    assert spawned == [("borrowed-1", devices[4:])]
    assert router.added == [("borrowed-1", tuple(devices[4:]))]
    # Burn clears, but the return waits for the clear sustain AND the
    # post-move cooldown.
    burn["on"] = False
    now["t"] = 1.1
    assert mover.evaluate() is None  # clear window opens
    now["t"] = 3.2
    assert mover.evaluate() is None  # sustained clear, still cooling
    now["t"] = 6.5
    assert mover.evaluate() == "to_training"
    assert mover.state == "training_full"
    assert router.removed == [("borrowed-1", True)]  # drained, not killed
    assert trainer.devices == devices  # full grant back
    assert mover.last_burn_cleared_s == pytest.approx(6.5)
    assert mover.moves == 2
    # A burn flicker after the move must restart the sustain window,
    # and the second loan still honors the cooldown.
    burn["on"] = True
    now["t"] = 7.0
    mover.evaluate()
    burn["on"] = False
    now["t"] = 7.5
    mover.evaluate()
    burn["on"] = True
    now["t"] = 8.0
    mover.evaluate()
    now["t"] = 9.1  # sustained > 1s, but inside the post-move cooldown
    assert mover.evaluate() is None
    now["t"] = 11.6
    assert mover.evaluate() == "to_serving"
    assert mover.state == "borrowed"


def test_chipmover_config_rejects_full_loan():
    with pytest.raises(ValueError, match="serve_share"):
        ChipMoverConfig(burn_sustain_s=1, clear_sustain_s=1,
                        cooldown_s=0, serve_share=1.0)


# ---------------------------------------------------------------------------
# elastic reshard-restore (the acceptance bar: 4 -> 8 -> 4 bitwise)
# ---------------------------------------------------------------------------


def _resnet_state(seed=0):
    model = ResNetTiny(num_classes=4)
    return create_train_state(
        jax.random.key(seed), model, jnp.zeros((1, 16, 16, 3)),
        optax.sgd(0.05, momentum=0.9),
    )


def _assert_payload_bitwise(got_state, want_payload):
    got = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)),
        state_payload(got_state),
    )
    got_leaves, got_def = jax.tree.flatten(got)
    want_leaves, want_def = jax.tree.flatten(want_payload)
    assert got_def == want_def
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_reshard_restore_4_to_8_to_4_bitwise():
    devs = jax.devices()
    assert len(devs) == 8, "conftest forces an 8-device CPU host"
    spec = MeshSpec(dp=1, fsdp=-1)  # fsdp=4 on 4 devices, 8 on 8
    mesh4 = cohort_mesh(devs[:4], spec)
    mesh8 = cohort_mesh(devs, spec)
    state = _resnet_state(0)
    want = jax.tree.map(np.asarray, state_payload(state))
    sh4 = elastic_shardings(mesh4, state, ELASTIC_RESNET_RULES)
    placed = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_payload(state), sh4,
        is_leaf=lambda x: not isinstance(x, dict),
    )
    state4 = state.replace(
        params=placed["params"], opt_state=placed["opt_state"],
        step=placed["step"],
    )
    with tempfile.TemporaryDirectory() as d:
        with AsyncCheckpointManager(os.path.join(d, "a")) as mgr:
            assert mgr.save(1, state4, block=True)
            mgr.wait_until_finished()
            restored8, _, _ = reshard_restore(
                mgr, _resnet_state(1), mesh8, ELASTIC_RESNET_RULES
            )
        _assert_payload_bitwise(restored8, want)
        # The restore genuinely RESHARDED: at least one leaf is split
        # across all 8 devices (not merely replicated wider).
        assert any(
            len(x.sharding.device_set) == 8
            and not x.sharding.is_fully_replicated
            for x in jax.tree.leaves(restored8.params)
            if hasattr(x, "sharding") and x.ndim > 0
        ), "no parameter was fsdp-split on the 8-device mesh"
        # And back down: 8 -> 4 restores the same bytes again.
        with AsyncCheckpointManager(os.path.join(d, "b")) as mgr2:
            assert mgr2.save(2, restored8, block=True)
            mgr2.wait_until_finished()
            restored4, _, _ = reshard_restore(
                mgr2, _resnet_state(2), mesh4, ELASTIC_RESNET_RULES
            )
        _assert_payload_bitwise(restored4, want)


def test_reshard_strict_coverage_raises_on_uncovered_leaf():
    devs = jax.devices()
    mesh = cohort_mesh(devs[:4], MeshSpec(dp=1, fsdp=-1))
    state = _resnet_state(0)
    # FSDP_RULES alone do not cover BatchNorm statistics: strict mode
    # must raise with the leaf's path named instead of silently
    # replicating it (which on a reshard would change placement).
    with pytest.raises(ValueError, match="batch_stats"):
        elastic_shardings(mesh, state, tuple(FSDP_RULES))


def test_elastic_trainer_resumes_across_mesh_shapes():
    """A cohort that checkpointed on 4 devices resumes on 8 (the
    restart path the chip mover drives), continuing toward
    total_steps with the grown mesh actually recorded."""
    devs = jax.devices()
    step_fn = make_classification_train_step()

    def make_batches():
        from tpudl.data import synthetic_classification_batches

        return synthetic_classification_batches(
            8, image_shape=(16, 16, 3), num_classes=4,
            num_batches=50, seed=7,
        )

    with tempfile.TemporaryDirectory() as d:
        mgr = AsyncCheckpointManager(d)
        t1 = ElasticTrainer(
            _resnet_state, step_fn, make_batches, mgr, devs[:4],
            total_steps=2, checkpoint_every=1,
            install_signal_handlers=False,
        )
        t1.start()
        t1.join(timeout_s=600)
        assert t1.error is None
        assert t1.finished and t1.steps_done == 2
        t2 = ElasticTrainer(
            _resnet_state, step_fn, make_batches, mgr, devs,
            total_steps=4, checkpoint_every=1,
            install_signal_handlers=False,
        )
        t2.start()
        t2.join(timeout_s=600)
        assert t2.error is None
        assert t2.finished and t2.steps_done == 4
        assert int(jax.device_get(t2.state.step)) == 4
        mgr.wait_until_finished()
        mgr.close()
    assert t1.mesh_shapes != t2.mesh_shapes, (
        "the resume must have compiled for the grown mesh"
    )


# ---------------------------------------------------------------------------
# mesh replicas behind the router (the acceptance bar: exact parity)
# ---------------------------------------------------------------------------


def test_router_parity_over_two_mesh_replicas(model_and_params):
    model, params = model_and_params
    devs = jax.devices()
    replicas = [
        MeshReplica(
            f"m{i}", model=model, params=params, prompt_len=PROMPT_LEN,
            devices=devs[4 * i:4 * i + 4],
            session_kwargs={"num_slots": 2},
        )
        for i in range(2)
    ]
    assert set(replicas[0].mesh_devices).isdisjoint(
        replicas[1].mesh_devices
    )
    assert all(len(r.mesh_devices) == 4 for r in replicas)
    requests = _greedy_requests(4, seed=3)
    with Router(replicas) as router:
        results = router.serve(list(requests), timeout_s=600.0)
    for req in requests:
        res = results[req.request_id]
        assert res.ok, (req.request_id, res.finish_reason)
        got = np.asarray(res.tokens)
        np.testing.assert_array_equal(
            got, _want(model, params, req)[: got.shape[0]],
            err_msg=f"{req.request_id} diverged on a mesh replica",
        )
    # Least-loaded placement spread the work: both meshes prefilled.
    assert all(r.session.engine.num_prefills > 0 for r in replicas)


@pytest.mark.needs_multiprocess
def test_pod_mesh_replica_multiprocess(model_and_params):
    """The pod-real tier: after ``jax.distributed.initialize`` (one
    process per host), the SAME session builder lays the tp axis over
    the global device list. Auto-skipped off-TPU — the CPU jaxlib
    cannot compile cross-process computations."""
    model, params = model_and_params
    session = build_mesh_session(
        model, params, PROMPT_LEN, devices=jax.devices(), num_slots=2
    )
    res = session.serve(
        [Request("pod0", [3, 1, 4, 1], max_new_tokens=4)]
    )["pod0"]
    assert res.ok


# ---------------------------------------------------------------------------
# migration over the transport layer
# ---------------------------------------------------------------------------


def test_migration_over_socket_endpoint_zero_reprefill(programs):
    """Source exports mid-stream, payload travels through a real TCP
    frame into the survivor's inbox, continuation is exact with zero
    prefill dispatches — all in one process (the cross-process variant
    below pays the second compile)."""
    src = _psession(programs)
    dst = _psession(programs)
    req = Request("sock0", [3, 5, 7, 11, 2], max_new_tokens=16)
    src.submit(req)
    for _ in range(4):
        src.engine.step()
    with MigrationEndpoint(
        lambda p: deliver_to_session(dst, p)
    ) as endpoint:
        sent = migrate_request(src, "sock0", address=endpoint.address)
        assert sent is not None and sent > 0
        deadline = time.monotonic() + 60.0
        while not dst.engine.migrate_inbox and endpoint.received == 0:
            assert time.monotonic() < deadline, "payload never arrived"
            time.sleep(0.005)
        while "sock0" not in dst.engine.results:
            if not dst.engine.step():
                time.sleep(0.005)
            assert time.monotonic() < deadline
    res = dst.engine.results["sock0"]
    assert res.finish_reason == "length"
    np.testing.assert_array_equal(
        np.asarray(res.tokens),
        _want(programs["model"], programs["params"], req),
    )
    assert dst.engine.num_prefills == 0
    assert endpoint.received == 1 and endpoint.errors == 0


def test_migration_cross_process_zero_reprefill(programs):
    """THE process-boundary acceptance: the survivor is a separately
    compiled python process; the payload crosses a socket; the child
    resumes byte-exact with zero prefill dispatches."""
    req = Request("xp0", [2, 9, 4, 7], max_new_tokens=12)
    src = _psession(programs)
    src.submit(req)
    for _ in range(3):
        src.engine.step()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "tests.fleet_helpers", "xp0"],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        hello = json.loads(proc.stdout.readline())
        port = int(hello["port"])
        sent = migrate_request(src, "xp0", address=("127.0.0.1", port))
        assert sent is not None and sent > 0
        out = json.loads(proc.stdout.readline())
        rc = proc.wait(timeout=600)
    finally:
        proc.kill()
    assert rc == 0, proc.stderr.read()
    assert "error" not in out, out
    assert out["finish_reason"] == "length"
    assert out["prefills"] == 0, (
        "the child engine re-paid prefill for a migrated request"
    )
    np.testing.assert_array_equal(
        np.asarray(out["tokens"], np.int64),
        _want(programs["model"], programs["params"], req),
        err_msg="continuation diverged across the process boundary",
    )


def test_draft_cache_migrates_with_the_request(model_and_params, programs):
    """The speculative failover contract, end to end through the spool
    transport: a speculating engine's payload carries the draft-cache
    remainder; the survivor resumes in lens-lockstep. Greedy parity
    alone cannot pin this (greedy correction repairs any draft), so
    the sharp check is a SAMPLED stream — its tokens depend on the
    draft's proposal distribution, which depends on the draft KV."""
    model, params = model_and_params

    def spec_session():
        return ServeSession.from_model(
            model, params, PROMPT_LEN, num_slots=2, paged=True,
            page_size=PAGE, spec_k=3,
        )

    greedy = Request("fg0", [3, 1, 4, 1, 5], max_new_tokens=12)
    sampled = Request("fs0", [5, 6, 7, 8], max_new_tokens=12,
                      temperature=0.8, seed=42)
    dst = spec_session()
    # The uninterrupted comparator runs on the DESTINATION session
    # (same compiled programs that will resume the migrated copies).
    want = dst.serve(
        [dataclasses.replace(greedy, request_id="wg0"),
         dataclasses.replace(sampled, request_id="ws0")]
    )
    src = spec_session()
    src.submit(dataclasses.replace(greedy))
    src.submit(dataclasses.replace(sampled))
    for _ in range(2):
        src.engine.step()
    for rid in ("fg0", "fs0"):
        assert rid not in src.engine.results, "migrate mid-stream"
    with tempfile.TemporaryDirectory() as d:
        channel = FileChannel(d)
        for rid in ("fg0", "fs0"):
            assert migrate_request(src, rid, channel=channel) > 0
        payloads = channel.drain()
    assert len(payloads) == 2
    assert {payload_request_id(p) for p in payloads} == {"fg0", "fs0"}
    emitted0 = registry().counter("spec_emitted_tokens").value
    prefills0 = dst.engine.num_prefills
    for p in payloads:
        deliver_to_session(dst, p)
    while ("fg0" not in dst.engine.results
           or "fs0" not in dst.engine.results):
        dst.engine.step()
    assert dst.engine.num_prefills == prefills0, (
        "draft migration must not re-pay prefill on either cache"
    )
    assert registry().counter("spec_emitted_tokens").value > emitted0, (
        "the survivor stopped speculating after the install"
    )
    assert dst.engine.results["fg0"].tokens == want["wg0"].tokens
    assert dst.engine.results["fs0"].tokens == want["ws0"].tokens, (
        "sampled stream diverged: the draft KV did not survive the move"
    )


def test_draftless_payload_refused_by_speculating_engine(
    model_and_params, programs
):
    """A payload from a non-speculating engine lacks the draft
    remainder: a speculating survivor must refuse it loudly (resuming
    with an empty draft cache breaks lens-lockstep) — and the reverse
    direction is fine: a non-speculating survivor ignores the rider."""
    model, params = model_and_params
    plain_src = _psession(programs)
    req = Request("nd0", [4, 4, 2, 1], max_new_tokens=10)
    plain_src.submit(req)
    for _ in range(3):
        plain_src.engine.step()
    payload = plain_src.engine.export_request("nd0")
    spec_dst = ServeSession.from_model(
        model, params, PROMPT_LEN, num_slots=2, paged=True,
        page_size=PAGE, spec_k=3,
    )
    with pytest.raises(MigrationCompatError, match="draft"):
        spec_dst.engine.install_migrated(payload)
    # Reverse: a speculating source's payload (with the draft rider)
    # installs cleanly into a plain engine — the rider is inert.
    spec_req = Request("sd0", [9, 8, 7, 6], max_new_tokens=10)
    spec_dst.submit(spec_req)
    spec_dst.engine.step()
    assert "sd0" not in spec_dst.engine.results
    spec_payload = spec_dst.engine.export_request("sd0")
    plain_dst = _psession(programs)
    assert plain_dst.engine.install_migrated(spec_payload) == "sd0"
    while plain_dst.engine.step():
        pass
    got = np.asarray(plain_dst.engine.results["sd0"].tokens)
    np.testing.assert_array_equal(
        got, _want(model, params, spec_req)[: got.shape[0]],
        err_msg="rider leaf corrupted a plain-engine install",
    )
    assert plain_dst.engine.num_prefills == 0
