"""Parquet converter: Petastorm-contract semantics (SURVEY.md §7.4 hard
part #2 — converter sharding/batching/epochs over pyarrow, no Spark)."""

import numpy as np
import pytest

from tpudl.data.converter import make_converter, prefetch_to_device, write_parquet


@pytest.fixture(scope="module")
def dataset_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("pq")
    rng = np.random.default_rng(0)
    write_parquet(
        str(d),
        {
            "image": rng.normal(size=(1000, 8, 8, 3)).astype(np.float32),
            "label": rng.integers(0, 10, size=(1000,)).astype(np.int64),
            "idx": np.arange(1000, dtype=np.int64),
        },
        rows_per_file=256,
    )
    return str(d)


def test_row_count_and_files(dataset_dir):
    conv = make_converter(dataset_dir)
    assert len(conv) == 1000
    assert len(conv.files) == 4  # ceil(1000/256)


def test_tensor_shape_restored(dataset_dir):
    conv = make_converter(dataset_dir)
    batch = next(conv.make_batch_iterator(32, shard_index=0, num_shards=1))
    assert batch["image"].shape == (32, 8, 8, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (32,)


def test_epoch_covers_all_rows_once(dataset_dir):
    conv = make_converter(dataset_dir)
    seen = []
    for batch in conv.make_batch_iterator(
        50, epochs=1, shard_index=0, num_shards=1, drop_last=False
    ):
        seen.extend(batch["idx"].tolist())
    assert sorted(seen) == list(range(1000))


def test_shards_disjoint_and_cover(dataset_dir):
    conv = make_converter(dataset_dir)
    shards = []
    for s in range(4):
        rows = []
        for batch in conv.make_batch_iterator(
            10, epochs=1, shard_index=s, num_shards=4, drop_last=False
        ):
            rows.extend(batch["idx"].tolist())
        shards.append(set(rows))
    union = set().union(*shards)
    assert union == set(range(1000))
    for a in range(4):
        for b in range(a + 1, 4):
            assert not (shards[a] & shards[b])


def test_drop_last(dataset_dir):
    conv = make_converter(dataset_dir)
    batches = list(
        conv.make_batch_iterator(64, epochs=1, shard_index=0, num_shards=1)
    )
    assert all(len(b["label"]) == 64 for b in batches)
    # 1000 rows, batch 64: 15 full batches when carrying remainders across files
    assert len(batches) == 15


def test_multiple_epochs(dataset_dir):
    conv = make_converter(dataset_dir)
    batches = list(
        conv.make_batch_iterator(100, epochs=2, shard_index=0, num_shards=1)
    )
    assert len(batches) == 20


def test_shuffle_determinism(dataset_dir):
    conv = make_converter(dataset_dir)

    def first_ids(seed):
        it = conv.make_batch_iterator(
            32, shuffle=True, seed=seed, shard_index=0, num_shards=1
        )
        return next(it)["idx"].tolist()

    assert first_ids(7) == first_ids(7)
    assert first_ids(7) != first_ids(8)
    # shuffled epoch still covers everything
    seen = []
    for b in conv.make_batch_iterator(
        50, shuffle=True, seed=3, epochs=1, shard_index=0, num_shards=1,
        drop_last=False,
    ):
        seen.extend(b["idx"].tolist())
    assert sorted(seen) == list(range(1000))


def test_column_selection(dataset_dir):
    conv = make_converter(dataset_dir)
    batch = next(
        conv.make_batch_iterator(
            16, shard_index=0, num_shards=1, columns=("label",)
        )
    )
    assert set(batch.keys()) == {"label"}


def test_prefetch_to_device_mesh(dataset_dir, mesh8):
    conv = make_converter(dataset_dir)
    it = conv.make_batch_iterator(64, epochs=1, shard_index=0, num_shards=1)
    count = 0
    for batch in prefetch_to_device(it, mesh=mesh8, prefetch=2):
        assert batch["image"].shape == (64, 8, 8, 3)
        # global array sharded over the batch axes
        assert batch["image"].sharding.spec[0] == ("dp", "fsdp")
        count += 1
    assert count == 15


def test_prefetch_propagates_errors(mesh8):
    def bad_iter():
        yield {"x": np.ones((4,), np.float32)}
        raise RuntimeError("reader exploded")

    # Prompt propagation (round-5 satellite): the error surfaces on the
    # next pull after the worker records it — possibly BEFORE queued good
    # batches, so don't assert the first batch arrives.
    it = prefetch_to_device(bad_iter(), mesh=None)
    with pytest.raises(RuntimeError, match="reader exploded"):
        list(it)


def test_shuffle_mixes_across_row_groups(tmp_path):
    """A label-sorted Parquet layout (common for Delta exports) must still
    yield mixed batches under shuffle — randomization has to span row
    groups, not just permute within one."""
    d = str(tmp_path / "sorted")
    labels = np.repeat(np.arange(10), 100)  # 1000 rows, sorted by label
    write_parquet(
        d,
        {"label": labels.astype(np.int64)},
        rows_per_file=500,
    )
    conv = make_converter(d)
    batch = next(
        conv.make_batch_iterator(
            100, shuffle=True, seed=0, shard_index=0, num_shards=1
        )
    )
    # Unshuffled, a 100-row batch holds exactly 1 label; shuffled over the
    # whole 1000-row buffer it should draw from most of the 10 classes.
    assert len(set(batch["label"].tolist())) >= 6


def test_all_shards_yield_identical_batch_counts(dataset_dir):
    """Per-file min-shard-length truncation: every process must take the
    same number of steps or stragglers hang peers inside collectives
    (ADVICE.md round-1). 1000 rows over 3 shards is the uneven case."""
    counts = []
    conv = make_converter(dataset_dir)
    for s in range(3):
        n = sum(
            1
            for _ in conv.make_batch_iterator(
                16, epochs=1, shard_index=s, num_shards=3
            )
        )
        counts.append(n)
    assert len(set(counts)) == 1, counts


def test_steps_per_epoch_matches_actual_yield(dataset_dir):
    """steps_per_epoch is what schedules are built against — it must equal
    the true drop_last yield (VERDICT.md round-1 weak #9)."""
    conv = make_converter(dataset_dir)
    for num_shards, batch in ((1, 64), (3, 16), (4, 10), (7, 8)):
        actual = sum(
            1
            for _ in conv.make_batch_iterator(
                batch, epochs=1, shard_index=0, num_shards=num_shards
            )
        )
        assert conv.steps_per_epoch(batch, num_shards=num_shards) == actual


def test_bad_shard_index(dataset_dir):
    conv = make_converter(dataset_dir)
    with pytest.raises(ValueError, match="shard_index"):
        next(conv.make_batch_iterator(8, shard_index=4, num_shards=4))


def test_missing_dir_error(tmp_path):
    with pytest.raises((ValueError, FileNotFoundError)):
        make_converter(str(tmp_path / "nope"))
