"""Multi-replica serving router (tpudl.serve.router).

The correctness bar stays test_serve's: whatever the router does —
least-loaded placement, sticky sessions, mid-stream failover when a
replica's /healthz goes 503, prefill/decode disaggregation — every
greedy request's final tokens must match ``generate()`` run on it
alone. On top of that: SLO burn sheds best-effort work at the door
(not queue overflow), an unready fleet sheds instead of hanging, and
the per-replica obs gauges publish what the router scraped.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.serve import (
    PrefillWorker,
    Replica,
    Request,
    Router,
    ServeSession,
)

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def _session(model, params, **kw):
    kw.setdefault("prompt_len", PROMPT_LEN)
    kw.setdefault("num_slots", 2)
    return ServeSession.from_model(model, params, **kw)


def _greedy_requests(n, seed=0, max_new_lo=6, max_new_hi=16, **kw):
    rng = np.random.default_rng(seed)
    return [
        Request(
            request_id=f"r{i}",
            input_ids=rng.integers(
                1, CFG.vocab_size, size=int(rng.integers(2, PROMPT_LEN + 1))
            ).tolist(),
            max_new_tokens=int(rng.integers(max_new_lo, max_new_hi)),
            **kw,
        )
        for i in range(n)
    ]


def _assert_generate_parity(model, params, requests, results):
    for req in requests:
        want = np.asarray(
            generate(
                model, params, jnp.asarray(req.input_ids)[None, :],
                max_new_tokens=req.max_new_tokens,
            )
        )[0]
        got = np.asarray(results[req.request_id].tokens)
        np.testing.assert_array_equal(
            got, want[: got.shape[0]],
            err_msg=f"request {req.request_id} diverged through the router",
        )


def test_router_round_trip_parity_and_balance(model_and_params):
    """Six greedy requests over two replicas: every result matches solo
    generate(), BOTH replicas did work (the token-weighted least-loaded
    books spread a burst submitted faster than health publishes), and
    the per-replica gauges carry the scraped view."""
    from tpudl.obs import registry

    model, params = model_and_params
    replicas = [
        Replica(f"r{i}", _session(model, params)) for i in range(2)
    ]
    requests = _greedy_requests(6, seed=1)
    with Router(replicas) as router:
        results = router.serve(requests, timeout_s=300.0)
    assert set(results) == {r.request_id for r in requests}
    _assert_generate_parity(model, params, requests, results)
    assert all(r.session.engine.num_prefills > 0 for r in replicas), (
        "placement starved one replica on a 6-request burst"
    )
    reg = registry()
    assert reg.gauge("serve_router_ready_replicas").value == 2
    assert reg.gauge("serve_replica_r0_ready").value == 1
    assert reg.gauge("serve_replica_r1_ready").value == 1


def test_router_sticky_sessions(model_and_params):
    """Requests sharing a session_key pin to one replica (KV/prefix
    affinity); keyless requests spread by load."""
    model, params = model_and_params
    replicas = [
        Replica(f"r{i}", _session(model, params)) for i in range(2)
    ]
    requests = [
        Request(f"s{i}", [3, 5, 7], max_new_tokens=4, session_key="user-1")
        for i in range(4)
    ]
    with Router(replicas) as router:
        owners = set()
        for req in requests:
            router.submit(req)
            owners.add(router._assigned[req.request_id][0])
        results = router.collect(timeout_s=300.0)
    assert len(owners) == 1, f"sticky key split across replicas: {owners}"
    assert router._sticky["user-1"] in {"r0", "r1"}
    assert all(r.finish_reason == "length" for r in results.values())


def test_router_failover_on_503_mid_stream(model_and_params):
    """One replica's /healthz goes 503 while its requests are mid-
    stream: the router requeues its outstanding work onto the survivor
    and every request still completes with solo-generate() tokens.
    Late results from the failed replica are dropped (the restarted
    copy is authoritative)."""
    model, params = model_and_params
    sessions = [_session(model, params) for _ in range(2)]
    # Slow every decode dispatch so work is still in flight at the flip
    # (the CPU tiny model would otherwise drain in milliseconds).
    for s in sessions:
        orig = s.engine.decode_call

        def slow(*args, _orig=orig):
            time.sleep(0.02)
            return _orig(*args)

        s.engine.decode_call = slow
    health = {"ok": True}
    r0 = Replica(
        "r0", sessions[0],
        health_fn=lambda: {
            **sessions[0].engine.health(), "healthy": health["ok"]
        },
    )
    r1 = Replica("r1", sessions[1])
    requests = _greedy_requests(4, seed=3, max_new_lo=12, max_new_hi=18)
    with Router([r0, r1], scrape_interval_s=0.0) as router:
        for req in requests:
            router.submit(req)
        assert any(
            owner == "r0" for owner, _ in router._assigned.values()
        ), "no request landed on r0 — the failover path is untested"
        time.sleep(0.1)  # let both replicas get into their streams
        health["ok"] = False  # /healthz -> 503 mid-stream
        results = router.collect(timeout_s=300.0)
    assert router.num_failovers >= 1
    assert not router._ready["r0"]
    assert set(results) == {r.request_id for r in requests}
    assert all(r.finish_reason == "length" for r in results.values())
    _assert_generate_parity(model, params, requests, results)


def test_router_unready_fleet_sheds_capacity(model_and_params):
    """No ready replica at all: submits shed as shed_capacity Results
    (outage is data, not an exception) and the router's own health
    source reports unhealthy."""
    model, params = model_and_params
    r0 = Replica(
        "r0", _session(model, params),
        health_fn=lambda: {"healthy": False, "error": "HTTP 503"},
    )
    with Router([r0], scrape_interval_s=0.0) as router:
        router.submit(Request("x", [1, 2], max_new_tokens=2))
        results = router.poll()
        assert results["x"].finish_reason == "shed_capacity"
        from tpudl.obs.exporter import _health_sources

        health = _health_sources["serve_router"]()
        assert health["healthy"] is False
        assert health["ready_replicas"] == 0


def test_router_slo_burn_sheds_best_effort_only(model_and_params):
    """While any replica's SLO burns, best-effort requests (priority >
    shed_priority_above) shed AT THE ROUTER as shed_slo; latency-class
    work keeps flowing. The autoscale hint gauge counts the burning
    replica."""
    from tpudl.obs import registry

    model, params = model_and_params
    r0 = Replica("r0", _session(model, params))
    with Router([r0], scrape_interval_s=0.0) as router:
        router._burning["r0"] = frozenset({"ttft_p95"})
        assert router.burning
        router.submit(
            Request("be", [1, 2], max_new_tokens=2, priority=1)
        )
        router.submit(
            Request("lat", [1, 2, 3], max_new_tokens=2, priority=0)
        )
        results = router.collect(timeout_s=300.0)
        assert results["be"].finish_reason == "shed_slo"
        assert results["be"].tokens == []
        assert results["lat"].finish_reason == "length"
        assert router._autoscale_hint() == 1
        assert registry().gauge("serve_router_autoscale_hint").value == 1
        router._burning["r0"] = frozenset()
        assert router._autoscale_hint() == 0


def test_router_disaggregated_prefill_parity(model_and_params):
    """Prefill/decode disaggregation over paged decode replicas: a
    dedicated PrefillWorker runs every batch-1 prefill and hands (row
    cache, first token) to decode replicas, which never pay a prefill
    dispatch — and the outputs still match solo generate()."""
    model, params = model_and_params
    replicas = [
        Replica(f"r{i}", _session(model, params, paged=True))
        for i in range(2)
    ]
    worker = PrefillWorker.from_model("p0", model, params, PROMPT_LEN)
    requests = _greedy_requests(6, seed=5)
    with Router(replicas, prefill=[worker]) as router:
        results = router.serve(requests, timeout_s=300.0)
    assert worker.num_prefills == 6
    for replica in replicas:
        # The decode engines never ran a local prefill dispatch — that
        # is the disaggregation contract (TPOT never pays a prefill).
        assert replica.session.engine.num_prefills == 0
    assert set(results) == {r.request_id for r in requests}
    _assert_generate_parity(model, params, requests, results)


def _slow_prefill_worker(model, params, sleep_s):
    """A PrefillWorker whose prefill dispatch takes ``sleep_s`` — the
    deterministic way to have work waiting in the prefill inbox while
    the fleet's state changes underneath it."""
    worker = PrefillWorker.from_model("p0", model, params, PROMPT_LEN)
    orig_call = worker.prefill_call

    def slow_call(*args):
        time.sleep(sleep_s)
        return orig_call(*args)

    worker.prefill_call = slow_call
    return worker


def test_router_disaggregated_deadline_and_sticky(model_and_params):
    """The disaggregated path keeps two AdmissionQueue contracts: a
    request whose deadline passes while queued behind a busy prefill
    tier is never started (shed_timeout with its real queue wait), and
    session_key stickiness binds at PLACEMENT — every request of a key
    decodes on the same replica even though the decode target is chosen
    at prefill completion."""
    model, params = model_and_params
    replicas = [
        Replica(f"r{i}", _session(model, params)) for i in range(2)
    ]
    seated = {name: [] for name in ("r0", "r1")}
    for replica in replicas:
        orig = replica.seat_prefilled

        def record(item, _name=replica.name, _orig=orig):
            seated[_name].append(item.entry.request.request_id)
            _orig(item)

        replica.seat_prefilled = record
    worker = _slow_prefill_worker(model, params, sleep_s=0.4)
    sticky = [
        Request(f"s{i}", [3, 5, 7], max_new_tokens=3, session_key="u1")
        for i in range(3)
    ]
    late = Request("late", [2, 4], max_new_tokens=3, deadline_s=0.05)
    with Router(replicas, prefill=[worker]) as router:
        for req in sticky:
            router.submit(req)
        router.submit(late)  # expires behind the 0.4s prefills ahead
        results = router.collect(timeout_s=300.0)
    assert results["late"].finish_reason == "shed_timeout"
    assert results["late"].queue_wait_s > 0.05
    assert all(results[r.request_id].finish_reason == "length"
               for r in sticky)
    owners = {
        name for name, rids in seated.items()
        if any(r.request_id in rids for r in sticky)
    }
    assert len(owners) == 1, (
        f"sticky key split across replicas at placement: {seated}"
    )
    assert "late" not in seated["r0"] + seated["r1"]  # never started


def test_router_disaggregated_unready_fleet_sheds_not_strands(
    model_and_params,
):
    """Every replica goes unready while a request sits in the prefill
    tier: placement sheds it as shed_capacity instead of parking it on
    a dead replica (failover only fires on a ready->unready edge, so a
    request placed on an already-unready replica would strand and
    collect() would spin forever)."""
    model, params = model_and_params
    health = {"ok": True}
    r0 = Replica(
        "r0", _session(model, params),
        health_fn=lambda: {"healthy": health["ok"]},
    )
    worker = _slow_prefill_worker(model, params, sleep_s=0.4)
    with Router([r0], prefill=[worker], scrape_interval_s=0.0) as router:
        router.submit(Request("x", [1, 2], max_new_tokens=2))
        health["ok"] = False  # fleet dies while x is still prefilling
        results = router.collect(timeout_s=300.0)
    assert results["x"].finish_reason == "shed_capacity"
    assert results["x"].tokens == []


def test_replica_scrape_over_real_http_healthz(model_and_params):
    """The scraped-placement contract end to end over HTTP: a Replica
    with ``health_url`` reads a live PR-6 ``/healthz`` endpoint (200 →
    ready, serves; raising source → 503 with the health JSON in the
    body → unready, sheds) — the same payload shape a real exporter
    publishes per replica process."""
    from tpudl.obs import exporter as obs_exporter

    model, params = model_and_params
    obs_exporter._reset_health_for_tests()
    session = _session(model, params)
    wedged = {"now": False}

    def engine_source():
        if wedged["now"]:
            raise RuntimeError("engine wedged")
        return {"healthy": True, **session.engine.health()}

    obs_exporter.register_health_source("serve_engine", engine_source)
    try:
        with obs_exporter.ObsExporter(port=0) as ex:
            url = f"http://127.0.0.1:{ex.port}/healthz"
            replica = Replica("r0", session, health_url=url)
            with Router([replica], scrape_interval_s=0.0) as router:
                requests = _greedy_requests(2, seed=7)
                results = router.serve(requests, timeout_s=300.0)
                assert all(
                    r.finish_reason == "length" for r in results.values()
                )
                scraped = replica.scrape()
                assert scraped["healthy"] is True
                assert scraped["num_slots"] == 2  # engine state rode along
                wedged["now"] = True  # /healthz now answers 503
                assert replica.scrape()["healthy"] is False
                router.submit(Request("x", [1, 2], max_new_tokens=2))
                assert router.poll()["x"].finish_reason == "shed_capacity"
    finally:
        obs_exporter.unregister_health_source("serve_engine")


def test_router_duplicate_and_empty_validation(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="at least one replica"):
        Router([])
    r0 = Replica("r0", _session(model, params))
    with Router([r0]) as router:
        router.submit(Request("dup", [1, 2], max_new_tokens=2))
        with pytest.raises(ValueError, match="duplicate"):
            router.submit(Request("dup", [1, 2], max_new_tokens=2))
        router.collect(timeout_s=300.0)
    sessions = [_session(model, params) for _ in range(2)]
    with pytest.raises(ValueError, match="unique"):
        Router([Replica("same", sessions[0]), Replica("same", sessions[1])])


def test_router_validates_at_the_door(model_and_params):
    """Router.submit admission-validates against the fleet's compiled
    shapes: an unservable request is a caller-visible ValueError — on
    the DISAGGREGATED path too, where it previously reached the prefill
    worker thread (negative pad -> crash) instead of the caller."""
    model, params = model_and_params
    too_long = Request(
        "long", list(range(1, PROMPT_LEN + 2)), max_new_tokens=2
    )
    r0 = Replica("r0", _session(model, params))
    with Router([r0]) as router:
        with pytest.raises(ValueError, match="prompt window"):
            router.submit(too_long)
        with pytest.raises(ValueError, match="max_new_tokens"):
            router.submit(Request("zero", [1, 2], max_new_tokens=0))
        assert not router._assigned and not router.results
    r1 = Replica("r1", _session(model, params))
    worker = PrefillWorker.from_model("p0", model, params, PROMPT_LEN)
    with Router([r1], prefill=[worker]) as router:
        with pytest.raises(ValueError, match="prompt window"):
            router.submit(too_long)
        assert len(worker) == 0 and not router._assigned


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_replica_crash_publishes_unhealthy_and_fails_over(model_and_params):
    """An exception escaping the replica loop (engine.step() raising)
    publishes unhealthy on the way out — the finally path — so the
    router fails its outstanding work over to survivors. Previously the
    crash left the last HEALTHY snapshot published forever: readiness
    never flipped, failover never fired, and collect() hung to
    timeout."""
    model, params = model_and_params
    r0 = Replica("r0", _session(model, params))
    r1 = Replica("r1", _session(model, params))
    armed = {"on": False}
    orig_step = r0.session.engine.step

    def exploding_step():
        if armed["on"]:
            raise RuntimeError("chip fell off")
        return orig_step()

    r0.session.engine.step = exploding_step
    requests = _greedy_requests(6, seed=23)
    with Router([r0, r1], scrape_interval_s=0.0) as router:
        for req in requests:
            router.submit(req)
        armed["on"] = True
        results = router.collect(timeout_s=300.0)
        assert router._ready["r0"] is False
    h = r0.scrape()
    assert h["healthy"] is False
    assert "crashed" in h.get("error", "")
    assert set(results) == {r.request_id for r in requests}
    assert all(res.finish_reason in ("eos", "length")
               for res in results.values())
    _assert_generate_parity(model, params, requests, results)


def test_replica_inbox_wait_counts_against_deadline(model_and_params):
    """A request's deadline budget spans the router hop: time queued in
    the REPLICA's inbox counts, so a deadline that expires there sheds
    (shed_timeout) instead of being served late — previously the
    replica restarted the full deadline_s from its own clock at
    session.submit time."""
    model, params = model_and_params
    r0 = Replica("r0", _session(model, params))
    orig_step = r0.session.engine.step

    def slow_step():
        time.sleep(0.3)
        return orig_step()

    r0.session.engine.step = slow_step
    with Router([r0]) as router:
        time.sleep(0.05)  # replica thread is inside a slow step
        router.submit(
            Request("late", [1, 2], max_new_tokens=2, deadline_s=0.1)
        )
        results = router.collect(timeout_s=300.0)
    assert results["late"].finish_reason == "shed_timeout"
    assert results["late"].queue_wait_s >= 0.1
    assert not router._deadline_at  # stamp cleaned up with the Result


def test_prefill_worker_failure_surfaces_not_kills(model_and_params):
    """One poisoned request blowing up mid-prefill surfaces as a
    ``failed:`` Result (assignment released — collect() doesn't hang)
    while the worker THREAD survives to prefill everything behind it
    in the inbox."""
    model, params = model_and_params
    r0 = Replica("r0", _session(model, params))
    worker = PrefillWorker.from_model("p0", model, params, PROMPT_LEN)
    orig_call = worker.prefill_call
    poison = {"armed": True}

    def flaky_call(p, ids, mask):
        if poison["armed"]:
            poison["armed"] = False
            raise RuntimeError("boom")
        return orig_call(p, ids, mask)

    worker.prefill_call = flaky_call
    good = _greedy_requests(3, seed=7)
    with Router([r0], prefill=[worker]) as router:
        router.submit(Request("bad", [1, 2, 3], max_new_tokens=4))
        for req in good:
            router.submit(req)
        results = router.collect(timeout_s=300.0)
    assert results["bad"].finish_reason.startswith("failed: RuntimeError")
    assert results["bad"].tokens == []
    assert worker.num_prefills == 3, "worker thread died on the poison"
    _assert_generate_parity(model, params, good, results)


def test_router_lock_order_monitor_clean_under_traffic(
    model_and_params, monkeypatch
):
    """TPUDL_DEBUG_LOCK_ORDER: real traffic over wrapped router +
    replica locks builds the live cross-object held-before graph with
    ZERO inversions, checked against the ranks the STATIC pass derives
    from the serve/obs sources (tpudl.analysis.concurrency) — the
    runtime half of the ISSUE-12 concurrency tier, on the exact
    subsystem whose _deadline_at/_books races motivated it."""
    import os

    import tpudl
    from tpudl.analysis import concurrency as conc

    tpudl_dir = os.path.dirname(tpudl.__file__)
    ranks = conc.derive_lock_ranks(
        [os.path.join(tpudl_dir, "serve"), os.path.join(tpudl_dir, "obs")]
    )
    monitor = conc.LockOrderMonitor(ranks=ranks)
    monkeypatch.setattr(conc, "_default_monitor", monitor)
    monkeypatch.setenv("TPUDL_DEBUG_LOCK_ORDER", "1")

    model, params = model_and_params
    replicas = [
        Replica(f"lo{i}", _session(model, params)) for i in range(2)
    ]
    # The flag was live at construction: the books and both replicas'
    # result locks must be wrapped.
    requests = _greedy_requests(4, seed=11)
    with Router(replicas) as router:
        assert isinstance(router._books, conc.OrderedLock)
        assert all(
            isinstance(r._results_lock, conc.OrderedLock)
            for r in replicas
        )
        results = router.serve(requests, timeout_s=300.0)
    assert set(results) == {r.request_id for r in requests}
    _assert_generate_parity(model, params, requests, results)
    assert monitor.violations == [], monitor.violations
    # The wrapper was live: the monitor saw the router's book
    # acquisitions. (The edge set is empty BY DESIGN — the router
    # never holds two locks at once, e.g. _harvest_one drains
    # replica.take() before entering the books; the monitor existing
    # is what keeps that property from silently regressing.)
    assert monitor.acquisitions > 0
