"""Ring attention parity on the fake 8-device CPU mesh.

The distributed-test mechanism of SURVEY.md §4.2: an sp>1 mesh out of
--xla_force_host_platform_device_count devices; parity vs the reference
einsum attention at the reference's tolerance discipline (reference
notebooks/cv/onnx_experiments.py:142-144 — explicit rtol/atol).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.attention import (
    attend,
    causal_mask,
    dot_product_attention,
    padding_mask,
)
from tpudl.ops.ring_attention import ring_attention
from tpudl.parallel.sharding import active_mesh
from tpudl.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=2, fsdp=1, sp=4, tp=1))


def _qkv(rng, b=4, s=64, h=2, d=16, dtype=jnp.float32):
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    return q, k, v


def _padding(rng, b, s):
    lengths = rng.integers(s // 2, s + 1, size=(b,))
    return jnp.asarray(
        (np.arange(s)[None, :] < lengths[:, None]).astype(np.int32)
    )


def test_parity_no_mask(sp_mesh, rng_np):
    q, k, v = _qkv(rng_np)
    ref = dot_product_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=sp_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_parity_padding_mask(sp_mesh, rng_np):
    q, k, v = _qkv(rng_np)
    mask2d = _padding(rng_np, 4, 64)
    ref = dot_product_attention(q, k, v, mask=padding_mask(mask2d))
    out = ring_attention(q, k, v, mask=padding_mask(mask2d), mesh=sp_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_parity_causal(sp_mesh, rng_np):
    q, k, v = _qkv(rng_np)
    ref = dot_product_attention(q, k, v, mask=causal_mask(64, 64))
    out = ring_attention(q, k, v, causal=True, mesh=sp_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_gradient_parity(sp_mesh, rng_np):
    q, k, v = _qkv(rng_np, s=32)
    mask2d = _padding(rng_np, 4, 32)

    def ref_loss(q, k, v):
        out = dot_product_attention(q, k, v, mask=padding_mask(mask2d))
        return jnp.sum(out * out)

    def ring_loss(q, k, v):
        out = ring_attention(q, k, v, mask=padding_mask(mask2d), mesh=sp_mesh)
        return jnp.sum(out * out)

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    ring_grads = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for name, rg, og in zip("qkv", ref_grads, ring_grads):
        np.testing.assert_allclose(
            np.asarray(og), np.asarray(rg), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_under_jit_with_sharded_inputs(sp_mesh, rng_np):
    """The production shape: jit with inputs placed sharded over sp, so the
    ring actually runs distributed (each device starts with its shard)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(rng_np)
    sh = NamedSharding(sp_mesh, P(("dp", "fsdp"), "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=sp_mesh))
    out = fn(qs, ks, vs)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_attend_dispatch_ring_under_active_mesh(sp_mesh, rng_np):
    q, k, v = _qkv(rng_np, s=32)
    with active_mesh(sp_mesh):
        out = attend(q, k, v, implementation="ring")
    ref = attend(q, k, v, implementation="reference")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_no_mesh_falls_back_to_reference(rng_np):
    """Unmeshed (model.init, single-device eval) the ring degenerates to
    reference attention instead of failing."""
    q, k, v = _qkv(rng_np, s=16)
    out = ring_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, mask=causal_mask(16, 16))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_indivisible_seq_rejected(sp_mesh, rng_np):
    q, k, v = _qkv(rng_np, s=30)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, v, mesh=sp_mesh)


class TestRingDropout:
    """Round-4: attention dropout under ring SP — post-softmax semantics
    with a DISTRIBUTED softmax (denominator accumulates undropped
    probabilities; only the numerator is masked per (q-shard, kv-block)
    tile). Low-width-bits masks run on CPU, so the fake mesh covers it."""

    def _qkv(self, seed=0, b=2, s=32, h=4, d=16):
        ks = jax.random.split(jax.random.key(seed), 3)
        return tuple(
            jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks
        )

    def test_deterministic_and_varies_by_key(self, sp_mesh):
        from tpudl.ops.ring_attention import ring_attention

        q, k, v = self._qkv()
        with active_mesh(sp_mesh):
            o1 = ring_attention(q, k, v, mesh=sp_mesh, dropout_rate=0.2,
                                dropout_rng=jax.random.key(5))
            o2 = ring_attention(q, k, v, mesh=sp_mesh, dropout_rate=0.2,
                                dropout_rng=jax.random.key(5))
            o3 = ring_attention(q, k, v, mesh=sp_mesh, dropout_rate=0.2,
                                dropout_rng=jax.random.key(6))
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
        assert not np.array_equal(np.asarray(o1), np.asarray(o3))

    def test_expectation_matches_base(self, sp_mesh):
        """Mean over keys approaches the no-dropout output — the check
        that would catch a dropped-denominator mistake (outputs would be
        biased high) or a missing rescale (biased low)."""
        from tpudl.ops.ring_attention import ring_attention

        q, k, v = self._qkv(seed=1)
        with active_mesh(sp_mesh):
            base = ring_attention(q, k, v, mesh=sp_mesh)
            f = jax.jit(
                lambda r: ring_attention(
                    q, k, v, mesh=sp_mesh, dropout_rate=0.2, dropout_rng=r
                )
            )
            acc = jnp.zeros_like(base)
            n = 64
            for i in range(n):
                acc = acc + f(jax.random.key(200 + i))
        err = float(jnp.mean(jnp.abs(acc / n - np.asarray(base))))
        assert err < 0.05, err

    def test_gradients_flow_and_are_deterministic(self, sp_mesh):
        """Autodiff through the scan replays identical masks: grads are
        finite and bit-stable per key."""
        from tpudl.ops.ring_attention import ring_attention

        q, k, v = self._qkv(seed=2)

        def loss(args):
            q_, k_, v_ = args
            with active_mesh(sp_mesh):
                out = ring_attention(
                    q_, k_, v_, mesh=sp_mesh, causal=True,
                    dropout_rate=0.2, dropout_rng=jax.random.key(9),
                )
            return jnp.sum(out ** 2)

        g1 = jax.grad(loss)((q, k, v))
        g2 = jax.grad(loss)((q, k, v))
        for a, b2 in zip(g1, g2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b2))
            assert np.isfinite(np.asarray(a)).all()

    def test_attend_dispatch(self, sp_mesh):
        from tpudl.ops.attention import attend

        q, k, v = self._qkv(seed=3)
        with active_mesh(sp_mesh):
            out = attend(q, k, v, implementation="ring", causal=True,
                         dropout_rate=0.2, dropout_rng=jax.random.key(0))
        assert np.isfinite(np.asarray(out)).all()


# ---------------------------------------------------------------------------
# Flash-bodied ring (round 5): per-tick Pallas kernel + (o, lse) merge.
# On CPU the kernel runs interpret-mode, so shapes stay small.
# ---------------------------------------------------------------------------


def _qkv_small(rng, b=2, s=32, h=2, d=8):
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_body_matches_reference_body(sp_mesh, rng_np, causal):
    q, k, v = _qkv_small(rng_np)
    mask2d = _padding(rng_np, 2, 32)
    want = ring_attention(
        q, k, v, mask=mask2d, causal=causal, mesh=sp_mesh,
        local_impl="reference",
    )
    got = ring_attention(
        q, k, v, mask=mask2d, causal=causal, mesh=sp_mesh,
        local_impl="flash",
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)
    # And both against the undistributed reference.
    from tpudl.ops.attention import combine_kv_causal_mask

    ref = dot_product_attention(
        q, k, v, mask=combine_kv_causal_mask(mask2d > 0, 32, 32, causal)
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_flash_body_grads_match_reference_body(sp_mesh, rng_np):
    """The merge is differentiable end to end (flash lse cotangent +
    scan + ppermute transpose): gradient parity vs the einsum body."""
    q, k, v = _qkv_small(rng_np)
    mask2d = _padding(rng_np, 2, 32)

    def loss(impl):
        def f(q_, k_, v_):
            out = ring_attention(
                q_, k_, v_, mask=mask2d, causal=True, mesh=sp_mesh,
                local_impl=impl,
            )
            return jnp.sum(out ** 2)

        return f

    g_ref = jax.grad(loss("reference"), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss("flash"), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name}",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_body_maskless_fast_path(sp_mesh, rng_np, causal):
    """mask=None threads NO kv-mask operand into the flash body (causal
    future blocks zero out via the merge weight): parity vs the einsum
    body and the undistributed reference."""
    q, k, v = _qkv_small(rng_np)
    want = ring_attention(q, k, v, causal=causal, mesh=sp_mesh,
                          local_impl="reference")
    got = ring_attention(q, k, v, causal=causal, mesh=sp_mesh,
                         local_impl="flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


def test_flash_body_validates_impl(sp_mesh, rng_np):
    q, k, v = _qkv_small(rng_np)
    with pytest.raises(ValueError, match="local_impl"):
        ring_attention(q, k, v, mesh=sp_mesh, local_impl="einsum")
