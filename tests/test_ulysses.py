"""Ulysses (all-to-all) sequence parallelism on the fake 8-device mesh.

Parity discipline as tests/test_ring_attention.py: sp>1 mesh from fake
CPU devices, outputs vs the reference einsum attention. With
local_impl="reference" (pinned in the exact-parity tests; also the CPU
default) ulysses runs the reference math verbatim on resharded
activations, so parity is exact at f32 — the flash local body gets its
own tolerance-based test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.attention import attend, causal_mask, padding_mask
from tpudl.ops.ulysses import ulysses_attention
from tpudl.parallel.sharding import active_mesh
from tpudl.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=2, fsdp=1, sp=4, tp=1))


def _qkv(seed, b=4, s=64, h=4, d=16):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks
    )


def _padding(seed, b, s):
    lengths = jax.random.randint(jax.random.key(seed), (b,), s // 2, s + 1)
    return (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)


def test_ulysses_matches_reference_no_mask(sp_mesh):
    q, k, v = _qkv(0)
    expected = attend(q, k, v)
    got = ulysses_attention(q, k, v, mesh=sp_mesh, local_impl="reference")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


def test_ulysses_padding_mask(sp_mesh):
    q, k, v = _qkv(1)
    am = _padding(2, 4, 64)
    expected = attend(q, k, v, mask=padding_mask(am))
    got = ulysses_attention(
        q, k, v, mask=padding_mask(am), mesh=sp_mesh, local_impl="reference"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


def test_ulysses_causal(sp_mesh):
    q, k, v = _qkv(3)
    expected = attend(q, k, v, mask=causal_mask(64, 64))
    got = ulysses_attention(
        q, k, v, causal=True, mesh=sp_mesh, local_impl="reference"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


def test_ulysses_grads_match(sp_mesh):
    q, k, v = _qkv(4)

    def loss_ref(q, k, v):
        return jnp.sum(attend(q, k, v) ** 2)

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, mesh=sp_mesh, local_impl="reference")
            ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_uly):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-4)


def test_ulysses_via_attend_with_active_mesh(sp_mesh):
    q, k, v = _qkv(5)
    with active_mesh(sp_mesh):
        got = attend(q, k, v, implementation="ulysses")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(attend(q, k, v)), atol=2e-5
    )


def test_ulysses_composes_with_tp(sp_mesh):
    """sp=2 x tp=2: heads split over tp, remaining heads over sp."""
    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=2, tp=2))
    q, k, v = _qkv(6, h=4)
    got = ulysses_attention(q, k, v, mesh=mesh, local_impl="reference")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(attend(q, k, v)), atol=2e-5
    )


def test_ulysses_degenerates_without_mesh():
    q, k, v = _qkv(7)
    got = ulysses_attention(q, k, v, causal=True, mesh=None)
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(attend(q, k, v, mask=causal_mask(64, 64))),
        atol=2e-5,
    )


def test_unmeshed_fallback_combines_causal_and_padding():
    """Regression: the no-mesh degenerate path must apply BOTH the padding
    mask and the causal triangle (and accept raw [B, S] masks)."""
    q, k, v = _qkv(20)
    am = _padding(21, 4, 64)
    expected = attend(
        q, k, v,
        mask=jnp.logical_and(padding_mask(am), causal_mask(64, 64)),
    )
    for m in (am, padding_mask(am)):  # [B, S] and [B, 1, 1, S] forms
        got = ulysses_attention(q, k, v, mask=m, causal=True, mesh=None)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), atol=2e-5
        )
        from tpudl.ops.ring_attention import ring_attention

        got_ring = ring_attention(q, k, v, mask=m, causal=True, mesh=None)
        np.testing.assert_allclose(
            np.asarray(got_ring), np.asarray(expected), atol=2e-5
        )


def test_ulysses_flash_local_impl(sp_mesh):
    """local_impl='flash' (the TPU long-context default — no [B, H/n, S, S]
    score tensor) runs the Pallas kernel per device; parity within flash
    tolerances, causal + padding."""
    q, k, v = _qkv(30)
    am = _padding(31, 4, 64)
    expected = attend(
        q, k, v,
        mask=jnp.logical_and(padding_mask(am), causal_mask(64, 64)),
    )
    got = ulysses_attention(
        q, k, v, mask=am, causal=True, mesh=sp_mesh, local_impl="flash"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-4
    )


def test_ulysses_validates(sp_mesh):
    q, k, v = _qkv(8, h=2)  # 2 heads not divisible by sp=4
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh=sp_mesh)
    q2, k2, v2 = _qkv(9, s=30)  # seq not divisible
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q2, k2, v2, mesh=sp_mesh)


def test_bert_with_ulysses_impl(sp_mesh):
    """Model-level wiring: BertConfig(attention_impl='ulysses') forward
    parity vs reference impl on the sp mesh."""
    from tpudl.models.bert import BERT_TINY, BertForSequenceClassification

    ids = jax.random.randint(jax.random.key(10), (4, 32), 0, 256)
    mask = jnp.ones_like(ids)

    def build(impl):
        cfg = BERT_TINY(
            vocab_size=256,
            num_heads=4,
            max_position_embeddings=64,
            hidden_dropout=0.0,
            attention_dropout=0.0,
            dtype=jnp.float32,
            attention_impl=impl,
        )
        return BertForSequenceClassification(cfg)

    params = build("reference").init(
        jax.random.key(11), ids, train=False
    )["params"]
    ref = build("reference").apply({"params": params}, ids, mask, train=False)
    with active_mesh(sp_mesh):
        got = build("ulysses").apply({"params": params}, ids, mask, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4)
