"""Ulysses (all-to-all) sequence parallelism on the fake 8-device mesh.

Parity discipline as tests/test_ring_attention.py: sp>1 mesh from fake
CPU devices, outputs vs the reference einsum attention. With
local_impl="reference" (pinned in the exact-parity tests; also the CPU
default) ulysses runs the reference math verbatim on resharded
activations, so parity is exact at f32 — the flash local body gets its
own tolerance-based test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.ops.attention import attend, causal_mask, padding_mask
from tpudl.ops.ulysses import ulysses_attention
from tpudl.parallel.sharding import active_mesh
from tpudl.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=2, fsdp=1, sp=4, tp=1))


def _qkv(seed, b=4, s=64, h=4, d=16):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks
    )


def _padding(seed, b, s):
    lengths = jax.random.randint(jax.random.key(seed), (b,), s // 2, s + 1)
    return (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)


def test_ulysses_matches_reference_no_mask(sp_mesh):
    q, k, v = _qkv(0)
    expected = attend(q, k, v)
    got = ulysses_attention(q, k, v, mesh=sp_mesh, local_impl="reference")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


def test_ulysses_padding_mask(sp_mesh):
    q, k, v = _qkv(1)
    am = _padding(2, 4, 64)
    expected = attend(q, k, v, mask=padding_mask(am))
    got = ulysses_attention(
        q, k, v, mask=padding_mask(am), mesh=sp_mesh, local_impl="reference"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


def test_ulysses_causal(sp_mesh):
    q, k, v = _qkv(3)
    expected = attend(q, k, v, mask=causal_mask(64, 64))
    got = ulysses_attention(
        q, k, v, causal=True, mesh=sp_mesh, local_impl="reference"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


def test_ulysses_grads_match(sp_mesh):
    q, k, v = _qkv(4)

    def loss_ref(q, k, v):
        return jnp.sum(attend(q, k, v) ** 2)

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, mesh=sp_mesh, local_impl="reference")
            ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_uly):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-4)


def test_ulysses_via_attend_with_active_mesh(sp_mesh):
    q, k, v = _qkv(5)
    with active_mesh(sp_mesh):
        got = attend(q, k, v, implementation="ulysses")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(attend(q, k, v)), atol=2e-5
    )


def test_ulysses_composes_with_tp(sp_mesh):
    """sp=2 x tp=2: heads split over tp, remaining heads over sp."""
    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=2, tp=2))
    q, k, v = _qkv(6, h=4)
    got = ulysses_attention(q, k, v, mesh=mesh, local_impl="reference")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(attend(q, k, v)), atol=2e-5
    )


def test_ulysses_degenerates_without_mesh():
    q, k, v = _qkv(7)
    got = ulysses_attention(q, k, v, causal=True, mesh=None)
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(attend(q, k, v, mask=causal_mask(64, 64))),
        atol=2e-5,
    )


def test_unmeshed_fallback_combines_causal_and_padding():
    """Regression: the no-mesh degenerate path must apply BOTH the padding
    mask and the causal triangle (and accept raw [B, S] masks)."""
    q, k, v = _qkv(20)
    am = _padding(21, 4, 64)
    expected = attend(
        q, k, v,
        mask=jnp.logical_and(padding_mask(am), causal_mask(64, 64)),
    )
    for m in (am, padding_mask(am)):  # [B, S] and [B, 1, 1, S] forms
        got = ulysses_attention(q, k, v, mask=m, causal=True, mesh=None)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), atol=2e-5
        )
        from tpudl.ops.ring_attention import ring_attention

        got_ring = ring_attention(q, k, v, mask=m, causal=True, mesh=None)
        np.testing.assert_allclose(
            np.asarray(got_ring), np.asarray(expected), atol=2e-5
        )


def test_ulysses_flash_local_impl(sp_mesh):
    """local_impl='flash' (the TPU long-context default — no [B, H/n, S, S]
    score tensor) runs the Pallas kernel per device; parity within flash
    tolerances, causal + padding."""
    q, k, v = _qkv(30)
    am = _padding(31, 4, 64)
    expected = attend(
        q, k, v,
        mask=jnp.logical_and(padding_mask(am), causal_mask(64, 64)),
    )
    got = ulysses_attention(
        q, k, v, mask=am, causal=True, mesh=sp_mesh, local_impl="flash"
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-4
    )


def test_ulysses_validates(sp_mesh):
    q, k, v = _qkv(8, h=2)  # 2 heads not divisible by sp=4
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh=sp_mesh)
    q2, k2, v2 = _qkv(9, s=30)  # seq not divisible
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q2, k2, v2, mesh=sp_mesh)


def test_bert_with_ulysses_impl(sp_mesh):
    """Model-level wiring: BertConfig(attention_impl='ulysses') forward
    parity vs reference impl on the sp mesh."""
    from tpudl.models.bert import BERT_TINY, BertForSequenceClassification

    ids = jax.random.randint(jax.random.key(10), (4, 32), 0, 256)
    mask = jnp.ones_like(ids)

    def build(impl):
        cfg = BERT_TINY(
            vocab_size=256,
            num_heads=4,
            max_position_embeddings=64,
            hidden_dropout=0.0,
            attention_dropout=0.0,
            dtype=jnp.float32,
            attention_impl=impl,
        )
        return BertForSequenceClassification(cfg)

    params = build("reference").init(
        jax.random.key(11), ids, train=False
    )["params"]
    ref = build("reference").apply({"params": params}, ids, mask, train=False)
    with active_mesh(sp_mesh):
        got = build("ulysses").apply({"params": params}, ids, mask, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4)


class TestUlyssesDropout:
    """Round-4: attention dropout under ulysses SP — exact per-head
    semantics on the post-all-to-all fully-local sequences, with each
    mesh slot folding its position into the key (independent masks).
    CPU path: local_impl='reference' (the jax.random low-width-bits
    masks, which run everywhere)."""

    def _qkv(self, seed=0, b=2, s=32, h=4, d=16):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(  # noqa: E731
            rng.normal(size=(b, s, h, d)), jnp.float32
        )
        return mk(), mk(), mk()

    def test_deterministic_and_varies_by_key(self, sp_mesh):
        from tpudl.ops.ulysses import ulysses_attention

        q, k, v = self._qkv()
        with active_mesh(sp_mesh):
            kwargs = dict(
                mesh=sp_mesh, local_impl="reference", dropout_rate=0.2,
            )
            o1 = ulysses_attention(
                q, k, v, dropout_rng=jax.random.key(5), **kwargs
            )
            o2 = ulysses_attention(
                q, k, v, dropout_rng=jax.random.key(5), **kwargs
            )
            o3 = ulysses_attention(
                q, k, v, dropout_rng=jax.random.key(6), **kwargs
            )
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
        assert not np.array_equal(np.asarray(o1), np.asarray(o3))

    def test_expectation_matches_base(self, sp_mesh):
        """Mean over keys approaches the no-dropout output — catches
        rescale and mask-correlation errors in one statistical check."""
        from tpudl.ops.ulysses import ulysses_attention

        q, k, v = self._qkv(seed=1)
        with active_mesh(sp_mesh):
            base = ulysses_attention(
                q, k, v, mesh=sp_mesh, local_impl="reference"
            )
            f = jax.jit(
                lambda r: ulysses_attention(
                    q, k, v, mesh=sp_mesh, local_impl="reference",
                    dropout_rate=0.2, dropout_rng=r,
                )
            )
            acc = jnp.zeros_like(base)
            n = 64
            for i in range(n):
                acc = acc + f(jax.random.key(100 + i))
        err = float(jnp.mean(jnp.abs(acc / n - np.asarray(base))))
        assert err < 0.05, err

    def test_attend_dispatch_and_mask(self, sp_mesh):
        """attend('ulysses', dropout) works with a padding mask; rng
        required; ring still refuses."""
        from tpudl.ops.attention import attend

        q, k, v = self._qkv(seed=2)
        pad = np.ones((2, 32), np.int32)
        pad[:, 28:] = 0
        with active_mesh(sp_mesh):
            out = attend(
                q, k, v, mask=jnp.asarray(pad), implementation="ulysses",
                dropout_rate=0.2, dropout_rng=jax.random.key(0),
            )
        assert np.isfinite(np.asarray(out)).all()
        with pytest.raises(ValueError, match="dropout_rng"):
            attend(q, k, v, implementation="ulysses", dropout_rate=0.2)
        with pytest.raises(ValueError, match="dropout_rng"):
            attend(q, k, v, implementation="ring", dropout_rate=0.2)
