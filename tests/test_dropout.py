"""Low-width-bits dropout (tpudl.ops.dropout) — the headline-path mask
generator (bench.py BERT step: 195 -> 168 ms/step vs bernoulli masks)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpudl.ops.dropout import Dropout, dropout, dropout_keep_mask


def test_keep_fraction_matches_rate():
    keep = dropout_keep_mask(jax.random.key(0), (512, 512), 0.1)
    frac = float(jnp.mean(keep.astype(jnp.float32)))
    # u8 quantization: exact expectation is 1 - 26/256 = 0.8984
    np.testing.assert_allclose(frac, 1.0 - 26 / 256, atol=3e-3)


def test_exact_path_is_bernoulli():
    k = jax.random.key(1)
    got = dropout_keep_mask(k, (64, 64), 0.25, exact=True)
    want = jax.random.bernoulli(k, 0.75, (64, 64))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_zero_rate_keeps_everything():
    assert bool(jnp.all(dropout_keep_mask(jax.random.key(2), (8, 8), 0.0)))
    x = jnp.ones((4, 4))
    np.testing.assert_array_equal(
        np.asarray(dropout(jax.random.key(3), x, 0.0)), np.asarray(x)
    )


def test_dropout_scales_survivors():
    x = jnp.ones((256, 256), jnp.float32)
    y = dropout(jax.random.key(4), x, 0.5)
    vals = np.unique(np.asarray(y))
    assert set(np.round(vals, 5)) <= {0.0, 2.0}
    # E[y] == 1 under inverted dropout
    np.testing.assert_allclose(float(jnp.mean(y)), 1.0, atol=0.05)


def test_module_respects_deterministic_and_rngs():
    m = Dropout(0.5)
    x = jnp.ones((32, 32))
    out_det = m.apply({}, x, deterministic=True)
    np.testing.assert_array_equal(np.asarray(out_det), np.asarray(x))
    out_a = m.apply({}, x, deterministic=False,
                    rngs={"dropout": jax.random.key(5)})
    out_b = m.apply({}, x, deterministic=False,
                    rngs={"dropout": jax.random.key(5)})
    out_c = m.apply({}, x, deterministic=False,
                    rngs={"dropout": jax.random.key(6)})
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
    assert not np.array_equal(np.asarray(out_a), np.asarray(out_c))
    assert float(jnp.mean((out_a == 0).astype(jnp.float32))) > 0.3


def test_gradient_masks_match_forward():
    x = jnp.ones((64, 64))
    k = jax.random.key(7)
    y, vjp = jax.vjp(lambda x: dropout(k, x, 0.5), x)
    (dx,) = vjp(jnp.ones_like(y))
    # Dropped positions get zero gradient; kept get the 1/(1-rate) scale.
    np.testing.assert_array_equal(np.asarray(dx != 0), np.asarray(y != 0))


def test_bert_trains_with_lowbits_dropout():
    """End-to-end: the BERT fine-tune (hidden + attention dropout 0.1 on
    the low-bits path) still learns."""
    import optax

    from tpudl.data.synthetic import synthetic_token_batches
    from tpudl.models.bert import BERT_TINY, BertForSequenceClassification
    from tpudl.train import create_train_state, make_classification_train_step

    model = BertForSequenceClassification(
        BERT_TINY(vocab_size=256, num_heads=2, dtype=jnp.float32)
    )
    batches = list(
        synthetic_token_batches(16, seq_len=16, vocab_size=256, num_batches=30)
    )
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.asarray(batches[0]["input_ids"]),
        optax.adamw(3e-3),
    )
    step = jax.jit(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        )
    )
    rng = jax.random.key(1)
    first = None
    for batch in batches:
        state, metrics = step(state, batch, rng)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
