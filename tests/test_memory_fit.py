"""configs[4] at its declared 8B scale, validated abstractly
(scripts/memory_fit.py): eval_shape + real NamedShardings, zero bytes
allocated. The deployment claim in BASELINE.json configs[4]
("FSDP->GSPMD sharding on v5p-64") becomes a computed, asserted fact."""

import importlib.util
import pathlib

spec = importlib.util.spec_from_file_location(
    "memory_fit",
    pathlib.Path(__file__).resolve().parents[1] / "scripts" / "memory_fit.py",
)
memory_fit = importlib.util.module_from_spec(spec)
spec.loader.exec_module(memory_fit)


def test_llama3_8b_lora_fits_conftest_mesh():
    """On the 8-fake-device conftest mesh (fit: fsdp=8) the full 8B LoRA
    state must fit the v5p bar; moments must be LoRA-small."""
    out = memory_fit.report("llama3_8b_lora", 8, 95.0)
    assert out["fits"], out
    bb = out["bytes_per_device"]
    assert out["params_total"] > 7e9  # genuinely the 8B shape
    assert out["params_trainable"] < 1e8  # LoRA + head only
    # Frozen base carries no moments: moments are orders of magnitude
    # below the master params.
    assert bb["opt_moments"] < bb["params"] / 10
    # Every component accounted and positive.
    for k in ("params", "opt_moments", "activations_upper_bound",
              "largest_allgathered_kernel"):
        assert bb[k] > 0, k
    assert bb["total"] == sum(
        bb[k] for k in bb if k != "total"
    )
