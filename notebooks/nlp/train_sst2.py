"""BERT / SST-2 fine-tune (BASELINE.json configs[1]).

The NLP workload the reference declares but never ships (reference
notebooks/nlp/README.md is an empty placeholder — SURVEY.md §0), built
TPU-native: Flax BERT through the attend() seam, Optax AdamW with warmup,
pjit over the (dp, fsdp, sp, tp) mesh, samples/sec + MFU reported the way
BASELINE.json `metric`/`north_star` ask.

--data-dir points at an SST-2-schema Parquet dataset fed through the
converter layer (pass --materialize to generate a synthetic one there
first); without it, an in-memory synthetic stream is used. In an
environment with network access, real pretrained weights drop in via
tpudl.models.params_from_hf_bert on a HuggingFace state_dict (parity
guaranteed by tests/test_bert.py::test_hf_weight_import_logits_parity).

Run: python notebooks/nlp/train_sst2.py [--steps N] [--model bert-tiny]
"""

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.converter import make_converter, prefetch_to_device
from tpudl.data.synthetic import synthetic_token_batches
from tpudl.models.registry import build_model
from tpudl.runtime import make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    fit,
    make_classification_train_step,
)
from tpudl.train.metrics import (
    compiled_flops,
    device_peak_flops,
    mfu,
    transformer_train_flops,
)
from tpudl.train.optim import make_optimizer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--model", type=str, default=None,
                        help="override config model (e.g. bert-tiny for smoke)")
    parser.add_argument("--seq-len", type=int, default=None)
    parser.add_argument("--data-dir", type=str, default=None,
                        help="SST-2-schema Parquet dataset directory")
    parser.add_argument("--materialize", action="store_true",
                        help="generate a synthetic dataset into --data-dir first")
    parser.add_argument(
        "--text-data", action="store_true",
        help="raw-text vertical: materialize a TEXT-schema dataset "
        "(sentence, label) under --data-dir, train a first-party WordPiece "
        "vocab on it, tokenize into an ids dataset, and fine-tune on that "
        "— text -> ids -> fine-tune in one command",
    )
    args = parser.parse_args()
    if (args.materialize or args.text_data) and not args.data_dir:
        parser.error("--materialize/--text-data require --data-dir")

    cfg = get_config("sst2_bert_base")
    if args.model:
        cfg = get_config("sst2_bert_base", model=args.model)
    batch_size = args.batch or cfg.global_batch_size
    seq_len = args.seq_len or cfg.seq_len

    model = build_model(cfg.model, cfg.num_classes)
    sample_ids = jnp.zeros((1, seq_len), jnp.int32)
    state = create_train_state(
        jax.random.key(cfg.seed),
        model,
        sample_ids,
        make_optimizer(cfg.optim),
    )
    num_params = sum(
        p.size for p in jax.tree_util.tree_leaves(state.params)
    )
    print(f"{cfg.model}: {num_params / 1e6:.1f}M params, batch {batch_size}, "
          f"seq {seq_len}")

    mesh = make_mesh(cfg.mesh)
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh,
        state,
        None,
    )

    warmup_steps = 2
    if args.text_data:
        import os

        from tpudl.data.datasets import (
            materialize_sst2_text,
            normalize_sst2_batch,
            tokenize_text_dataset,
        )
        from tpudl.data.tokenizer import (
            WordPieceTokenizer,
            build_wordpiece_vocab,
        )

        from tpudl.data.converter import make_converter as _mk

        text_dir = os.path.join(args.data_dir, "text")
        ids_dir = os.path.join(args.data_dir, "ids")
        vocab_path = os.path.join(args.data_dir, "vocab.txt")
        if os.path.isdir(ids_dir) and not args.materialize:
            # Petastorm contract: materialize once, train many. Pass
            # --materialize to force regeneration.
            print(f"reusing tokenized dataset {ids_dir} (vocab {vocab_path})")
            conv = _mk(ids_dir)
        else:
            text_conv = materialize_sst2_text(text_dir, num_rows=8_192)
            corpus = (
                str(s)
                for b in text_conv.make_batch_iterator(
                    1024, epochs=1, shuffle=False, drop_last=False,
                    columns=("sentence",),
                )
                for s in b["sentence"]
            )
            tok = WordPieceTokenizer(build_wordpiece_vocab(corpus, 4096))
            tok.save_vocab(vocab_path)
            print(f"trained WordPiece vocab ({len(tok.vocab)} tokens) -> "
                  f"{vocab_path}")
            conv = tokenize_text_dataset(
                text_dir, ids_dir, tok, seq_len=seq_len
            )
        raw = (
            normalize_sst2_batch(b)
            for b in conv.make_batch_iterator(
                batch_size, epochs=None, shuffle=True, seed=cfg.seed
            )
        )
    elif args.data_dir:
        from tpudl.data.datasets import materialize_sst2_like, normalize_sst2_batch

        if args.materialize:
            conv = materialize_sst2_like(
                args.data_dir, num_rows=8_192, seq_len=seq_len,
                vocab_size=model.cfg.vocab_size,
            )
        else:
            conv = make_converter(args.data_dir)
        raw = (
            normalize_sst2_batch(b)
            for b in conv.make_batch_iterator(
                batch_size, epochs=None, shuffle=True, seed=cfg.seed
            )
        )
    else:
        raw = synthetic_token_batches(
            batch_size,
            seq_len=seq_len,
            vocab_size=model.cfg.vocab_size,
            num_classes=cfg.num_classes,
            seed=cfg.seed,
            num_batches=args.steps + warmup_steps,
        )
    # Prefetch either stream: explicit placement overlaps the host->device
    # transfer with compute (jit's implicit numpy-arg transfer is
    # pathologically slow on relay-attached devices).
    batches = prefetch_to_device(raw, mesh=mesh)
    rng = jax.random.key(cfg.seed + 1)

    def log(i, metrics):
        print(f"step {i}: loss {metrics['loss']:.4f} acc {metrics['accuracy']:.3f}")

    # Warmup outside the timing window, CLOSED BY A READBACK: the first
    # call pays the XLA compile synchronously, but the compiled program's
    # upload + first execution on the (relay-attached) chip happens
    # asynchronously behind the dispatch — without the scalar sync it
    # lands inside the timed window and deflates samples/sec and MFU
    # (the BASELINE.json metrics are steady-state quantities).
    batches = iter(batches)
    for _ in range(warmup_steps):
        state, warm = step(state, next(batches), rng)
    float(warm["loss"])
    state, metrics, info = fit(
        step, state, batches, rng, num_steps=args.steps,
        log_every=cfg.log_every, logger=log,
    )
    print(f"final: {metrics}")

    samples_per_sec = batch_size * info["steps"] / info["seconds"]
    # FLOPs from the compiled executable; 6ND transformer estimate as fallback.
    flops = None
    try:
        example = next(synthetic_token_batches(
            batch_size, seq_len=seq_len, vocab_size=model.cfg.vocab_size,
            num_batches=1,
        ))
        flops = compiled_flops(step.jitted.lower(state, example, rng))
    except Exception:
        pass
    if flops is None:
        flops = transformer_train_flops(num_params, batch_size * seq_len)
    step_seconds = info["seconds"] / max(info["steps"], 1)
    print(
        f"throughput ~{samples_per_sec:.0f} samples/sec over {info['steps']} "
        f"steady-state steps (compile excluded); "
        f"MFU ~{100 * mfu(flops, step_seconds, jax.device_count()):.1f}% "
        f"(peak {device_peak_flops() / 1e12:.0f} TFLOP/s/chip)"
    )


if __name__ == "__main__":
    main()
