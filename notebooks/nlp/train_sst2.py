"""BERT fine-tune workloads (BASELINE.json configs[1] and configs[3]).

  python notebooks/nlp/train_sst2.py                              # configs[1]
  python notebooks/nlp/train_sst2.py --config bert_large_v4_32    # configs[3]

The NLP workload the reference declares but never ships (reference
notebooks/nlp/README.md is an empty placeholder — SURVEY.md §0), built
TPU-native: Flax BERT through the attend() seam, Optax AdamW with warmup,
pjit over the (dp, fsdp, sp, tp) mesh, samples/sec + MFU reported the way
BASELINE.json `metric`/`north_star` ask. configs[3] is the
HorovodRunner -> TpuDistributor migration config: its declared
(dp=-1, fsdp=4) mesh clamps to the local chip count, and its global
batch fits small meshes via gradient accumulation (--accum).

--data-dir points at an SST-2-schema Parquet dataset fed through the
converter layer (pass --materialize to generate a synthetic one there
first); without it, an in-memory synthetic stream is used. In an
environment with network access, real pretrained weights drop in via
tpudl.models.params_from_hf_bert on a HuggingFace state_dict (parity
guaranteed by tests/test_bert.py::test_hf_weight_import_logits_parity).

Run: python notebooks/nlp/train_sst2.py [--steps N] [--model bert-tiny]
"""

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.converter import make_converter, prefetch_to_device
from tpudl.data.datasets import eval_stream, split_train_eval
from tpudl.data.synthetic import synthetic_token_batches
from tpudl.models.registry import build_model
from tpudl.parallel.sharding import strategy_rules
from tpudl.runtime import apply_platform_env, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    evaluate,
    fit,
    make_classification_eval_step,
    make_classification_train_step,
)
from tpudl.train.metrics import (
    compiled_flops,
    device_peak_flops,
    mfu,
    transformer_train_flops,
)
from tpudl.train.optim import make_optimizer

apply_platform_env()


#: NLP fine-tune configs this driver accepts (configs[1] and configs[3];
#: configs[4]'s LoRA vertical is notebooks/nlp/finetune_lora.py).
NLP_CONFIGS = ("sst2_bert_base", "bert_large_v4_32")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, default="sst2_bert_base",
                        choices=NLP_CONFIGS,
                        help="BASELINE.json config to drive; the declared "
                        "mesh auto-clamps to the local device count "
                        "(MeshSpec.fit), so bert_large_v4_32 trains on one "
                        "chip and shards fsdp=4 on a pod")
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--accum", type=int, default=None,
                        help="gradient-accumulation microbatches "
                        "(default: config accum_steps)")
    parser.add_argument("--remat", type=str, default=None,
                        choices=["none", "layer", "attention", "dots"],
                        help="rematerialization scope for BERT models "
                        "(default: model default; 'dots' = layer remat "
                        "with the dots_saveable policy)")
    parser.add_argument("--model", type=str, default=None,
                        help="override config model (e.g. bert-tiny for smoke)")
    parser.add_argument("--seq-len", type=int, default=None)
    parser.add_argument("--data-dir", type=str, default=None,
                        help="SST-2-schema Parquet dataset directory")
    parser.add_argument("--materialize", action="store_true",
                        help="generate a synthetic dataset into --data-dir first")
    parser.add_argument("--ingest", type=str, default=None,
                        help="REAL GLUE SST-2 TSV (train.tsv or the SST-2 "
                        "directory): ingested into the --text-data text "
                        "Parquet before tokenization (tpudl.data.ingest)")
    parser.add_argument(
        "--text-data", action="store_true",
        help="raw-text vertical: materialize a TEXT-schema dataset "
        "(sentence, label) under --data-dir, train a first-party WordPiece "
        "vocab on it, tokenize into an ids dataset, and fine-tune on that "
        "— text -> ids -> fine-tune in one command",
    )
    parser.add_argument("--strategy", type=str, default=None,
                        help="override config strategy: dp | fsdp | tp | "
                        "fsdp+tp | pp | pp+fsdp")
    parser.add_argument("--mesh", type=str, default=None,
                        help="dp,fsdp,sp,tp[,pp[,ep]] (e.g. 2,1,1,1,4)")
    parser.add_argument("--microbatches", type=int, default=4,
                        help="GPipe microbatches (strategy=pp only)")
    parser.add_argument("--checkpoint-dir", type=str, default=None,
                        help="CheckpointManager directory: saves every "
                        "--checkpoint-every steps and RESUMES from the "
                        "latest checkpoint on restart")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    parser.add_argument("--log-dir", type=str, default=None,
                        help="MetricLogger directory (JSONL + TensorBoard)")
    parser.add_argument("--eval-steps", type=int, default=8,
                        help="held-out eval batches after training (0 = off)")
    parser.add_argument("--mfu-compiled", action="store_true",
                        help="exact compiled-cost FLOPs for the MFU print "
                        "(pays a second full XLA compile; default: 6ND "
                        "estimate)")
    args = parser.parse_args()
    if (args.materialize or args.text_data) and not args.data_dir:
        parser.error("--materialize/--text-data require --data-dir")
    if args.ingest and not args.text_data:
        parser.error("--ingest feeds the raw-text vertical: add --text-data")

    overrides = {}
    if args.model:
        overrides["model"] = args.model
    if args.strategy:
        overrides["strategy"] = args.strategy
    if args.checkpoint_dir:
        overrides["checkpoint_dir"] = args.checkpoint_dir
    if args.mesh:
        from tpudl.runtime import MeshSpec

        overrides["mesh"] = MeshSpec(
            *(int(x) for x in args.mesh.split(","))
        )
    cfg = get_config(args.config, **overrides)
    batch_size = args.batch or cfg.global_batch_size
    seq_len = args.seq_len or cfg.seq_len
    accum = args.accum if args.accum is not None else cfg.accum_steps

    model_kwargs = {}
    if args.remat:
        from tpudl.models.bert import remat_options

        model_kwargs.update(remat_options(args.remat))

    # An explicit --mesh is taken literally; the config's declared mesh
    # clamps to whatever devices this host actually has.
    mesh_spec = cfg.mesh if args.mesh else cfg.mesh.fit(jax.device_count())
    mesh = make_mesh(mesh_spec)
    if cfg.strategy in ("pp", "pp+fsdp"):
        from tpudl.models.registry import build_pipelined_model

        model = build_pipelined_model(
            cfg.model, cfg.num_classes,
            num_stages=mesh.shape["pp"], num_microbatches=args.microbatches,
            param_fsdp=cfg.strategy == "pp+fsdp",
            **model_kwargs,
        )
    else:
        model = build_model(cfg.model, cfg.num_classes, **model_kwargs)
    sample_ids = jnp.zeros((1, seq_len), jnp.int32)
    state = create_train_state(
        jax.random.key(cfg.seed),
        model,
        sample_ids,
        make_optimizer(cfg.optim),
    )
    num_params = sum(
        p.size for p in jax.tree_util.tree_leaves(state.params)
    )
    print(f"{cfg.name}: {cfg.model} {num_params / 1e6:.1f}M params, "
          f"batch {batch_size} (accum {accum}), seq {seq_len}, "
          f"strategy {cfg.strategy}, mesh {dict(mesh.shape)}")

    rules = strategy_rules(cfg.strategy)
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label",
            accum_steps=accum,
        ),
        mesh,
        state,
        rules,
    )

    warmup_steps = 2
    if args.text_data:
        import os

        from tpudl.data.datasets import (
            materialize_sst2_text,
            normalize_sst2_batch,
            tokenize_text_dataset,
        )
        from tpudl.data.tokenizer import (
            WordPieceTokenizer,
            build_wordpiece_vocab,
        )

        from tpudl.data.converter import make_converter as _mk

        text_dir = os.path.join(args.data_dir, "text")
        ids_dir = os.path.join(args.data_dir, "ids")
        vocab_path = os.path.join(args.data_dir, "vocab.txt")
        if os.path.isdir(ids_dir) and not (args.materialize or args.ingest):
            # Petastorm contract: materialize once, train many. Pass
            # --materialize to force regeneration.
            print(f"reusing tokenized dataset {ids_dir} (vocab {vocab_path})")
            conv = _mk(ids_dir)
        else:
            if args.ingest:
                from tpudl.data.ingest import ingest_sst2_tsv

                text_conv = ingest_sst2_tsv(args.ingest, text_dir)
                print(f"ingested {args.ingest} -> {text_dir} "
                      f"({text_conv.num_rows} rows)")
            else:
                text_conv = materialize_sst2_text(text_dir, num_rows=8_192)
            corpus = (
                str(s)
                for b in text_conv.make_batch_iterator(
                    1024, epochs=1, shuffle=False, drop_last=False,
                    columns=("sentence",),
                )
                for s in b["sentence"]
            )
            tok = WordPieceTokenizer(build_wordpiece_vocab(corpus, 4096))
            tok.save_vocab(vocab_path)
            print(f"trained WordPiece vocab ({len(tok.vocab)} tokens) -> "
                  f"{vocab_path}")
            conv = tokenize_text_dataset(
                text_dir, ids_dir, tok, seq_len=seq_len
            )
        conv, eval_conv = split_train_eval(conv)
        # Wire casts run in the prefetcher's assembly pool (parallel,
        # outside the source lock), not inside the source iterator.
        raw = conv.make_batch_iterator(
            batch_size, epochs=None, shuffle=True, seed=cfg.seed
        )
        host_transform = normalize_sst2_batch
        eval_raw = eval_stream(
            eval_conv, batch_size, normalize_sst2_batch,
            batch_divisor=mesh.shape["dp"] * mesh.shape["fsdp"],
        )
    elif args.data_dir:
        from tpudl.data.datasets import materialize_sst2_like, normalize_sst2_batch

        if args.materialize:
            conv = materialize_sst2_like(
                args.data_dir, num_rows=8_192, seq_len=seq_len,
                vocab_size=model.cfg.vocab_size,
            )
        else:
            conv = make_converter(args.data_dir)
        conv, eval_conv = split_train_eval(conv)
        # Wire casts run in the prefetcher's assembly pool (parallel,
        # outside the source lock), not inside the source iterator.
        raw = conv.make_batch_iterator(
            batch_size, epochs=None, shuffle=True, seed=cfg.seed
        )
        host_transform = normalize_sst2_batch
        eval_raw = eval_stream(
            eval_conv, batch_size, normalize_sst2_batch,
            batch_divisor=mesh.shape["dp"] * mesh.shape["fsdp"],
        )
    else:
        host_transform = None  # synthetic stream is already wire-ready
        raw = synthetic_token_batches(
            batch_size,
            seq_len=seq_len,
            vocab_size=model.cfg.vocab_size,
            num_classes=cfg.num_classes,
            seed=cfg.seed,
            num_batches=args.steps + warmup_steps,
        )
        # Held-out synthetic stream: same distribution, disjoint seed.
        eval_raw = lambda: synthetic_token_batches(  # noqa: E731
            batch_size,
            seq_len=seq_len,
            vocab_size=model.cfg.vocab_size,
            num_classes=cfg.num_classes,
            seed=cfg.seed + 10_000,
            num_batches=args.eval_steps,
        )
    # Checkpoint/resume (SURVEY.md §5.3/§5.4): restore the latest state
    # if the directory has one; fast-forward the stream so a killed run
    # rerun with the same flags continues where it stopped.
    ckpt_mgr = None
    start_step = 0
    if cfg.checkpoint_dir:
        from tpudl.checkpoint import CheckpointManager
        from tpudl.train import resume_latest

        ckpt_mgr = CheckpointManager(cfg.checkpoint_dir)
        state, start_step = resume_latest(ckpt_mgr, state, mesh, rules)
        if start_step:
            print(f"resumed from step {start_step} ({cfg.checkpoint_dir})")

    # Fast-forward a resumed run on the HOST side (before device
    # prefetch), so skipped batches never pay a transfer; then prefetch:
    # explicit placement overlaps the host->device transfer with compute
    # (jit's implicit numpy-arg transfer is pathologically slow on
    # relay-attached devices).
    import itertools

    if start_step:
        raw = itertools.islice(iter(raw), start_step, None)
    # The int64->int32 token casts run in the prefetcher's assembly pool
    # (outside the source lock, overlapped with the transfer stage);
    # depth autotunes off data-wait (TPUDL_PREFETCH_DEPTH pins it).
    batches = prefetch_to_device(
        raw, mesh=mesh, transform=host_transform,
        assembly_workers=2 if host_transform else 1,
    )
    rng = jax.random.key(cfg.seed + 1)

    logger = None
    if args.log_dir:
        from tpudl.train import MetricLogger

        logger = MetricLogger(args.log_dir)

    def log(i, metrics):
        print(f"step {i}: loss {metrics['loss']:.4f} acc {metrics['accuracy']:.3f}")
        if logger:
            logger(start_step + i, metrics)

    # Warmup outside the timing window, CLOSED BY A READBACK: the first
    # call pays the XLA compile synchronously, but the compiled program's
    # upload + first execution on the (relay-attached) chip happens
    # asynchronously behind the dispatch — without the scalar sync it
    # lands inside the timed window and deflates samples/sec and MFU
    # (the BASELINE.json metrics are steady-state quantities).
    batches = iter(batches)
    # --steps is the TOTAL optimizer-step budget (warmup included); a run
    # resumed at or past the budget trains zero further steps.
    budget = max(args.steps - start_step, 0)
    wsteps = min(warmup_steps, budget)
    remaining = budget - wsteps
    warm = None
    for _ in range(wsteps):
        state, warm = step(state, next(batches), rng)
    if warm is not None:
        float(warm["loss"])
    state, metrics, info = fit(
        step, state, itertools.islice(batches, remaining), rng,
        log_every=cfg.log_every, logger=log,
        checkpoint_manager=ckpt_mgr,
        checkpoint_every=args.checkpoint_every if ckpt_mgr else 0,
    )
    print(f"final: {metrics}")

    if args.eval_steps:
        eval_step = compile_step(
            make_classification_eval_step(
                input_keys=("input_ids", "attention_mask"), label_key="label"
            ),
            mesh,
            state,
            rules,
            has_rng=False,
        )
        eval_metrics = evaluate(
            eval_step, state, eval_raw(), num_steps=args.eval_steps
        )
        print(
            f"held-out eval (<= {args.eval_steps} batches): "
            f"loss {eval_metrics['loss']:.4f} "
            f"accuracy {eval_metrics['accuracy']:.3f}"
        )
        if logger:
            logger(start_step + info["steps"],
                   {f"eval_{k}": v for k, v in eval_metrics.items()})
    if logger:
        logger.close()

    if info["steps"] == 0:
        from tpudl.train import finalize_zero_step_run

        print(finalize_zero_step_run(ckpt_mgr, state, wsteps))
        return
    samples_per_sec = batch_size * info["steps"] / info["seconds"]
    # 6ND transformer estimate by default (the BASELINE.md basis);
    # --mfu-compiled opts into exact compiled-cost FLOPs, which pays a
    # SECOND full XLA compile via lower().compile() — minutes at
    # BERT-large scale, not worth it on every training run.
    flops = None
    if args.mfu_compiled:
        try:
            example = next(synthetic_token_batches(
                batch_size, seq_len=seq_len, vocab_size=model.cfg.vocab_size,
                num_batches=1,
            ))
            flops = compiled_flops(step.jitted.lower(state, example, rng))
        except Exception:
            pass
    if flops is None:
        flops = transformer_train_flops(num_params, batch_size * seq_len)
    step_seconds = info["seconds"] / max(info["steps"], 1)
    print(
        f"throughput ~{samples_per_sec:.0f} samples/sec over {info['steps']} "
        f"steady-state steps (compile excluded); "
        f"MFU ~{100 * mfu(flops, step_seconds, jax.device_count()):.1f}% "
        f"(peak {device_peak_flops() / 1e12:.0f} TFLOP/s/chip)"
    )


if __name__ == "__main__":
    main()
