"""Llama LoRA fine-tune (BASELINE.json configs[4], the GSPMD stretch).

The reference declares this workload only through the driver north-star
(nothing exists in the reference tree — SURVEY.md §0). TPU-native shape:
a Llama decoder with rank-r adapters (tpudl.models.lora), frozen base via
optax.multi_transform (no optimizer moments for frozen weights — the
memory win that fits 8B), sharded by composed LORA+TP+FSDP rules over the
(dp, fsdp, sp, tp) mesh, classification from the last non-pad token.

Defaults run the tiny model so the script executes anywhere (including
the 8-device fake CPU mesh); pass --model llama3-8b-lora on a pod slice.
--text-data runs the Llama-family raw-text vertical: text corpus ->
first-party byte-level BPE (tpudl.data.bpe) -> ids Parquet -> LoRA
fine-tune, in one command (--ingest points it at a real GLUE SST-2 TSV).

Run: python notebooks/nlp/finetune_lora.py [--steps N] [--model llama-tiny-lora]
"""

import argparse
import itertools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.synthetic import synthetic_token_batches
from tpudl.models.lora import (
    LORA_RULES,
    compose_rules,
    lora_optimizer,
    trainable_param_count,
)
from tpudl.models.registry import build_model
from tpudl.parallel.sharding import TP_TRANSFORMER_RULES
from tpudl.runtime import MeshSpec, apply_platform_env, make_mesh
from tpudl.train import (
    MetricLogger,
    TrainState,
    compile_step,
    fit,
    make_classification_train_step,
)
from tpudl.train.optim import make_optimizer

apply_platform_env()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--model", type=str, default="llama-tiny-lora")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--mesh", type=str, default=None,
                        help="dp,fsdp,sp,tp (e.g. 2,2,1,2); default all-dp")
    parser.add_argument("--log-dir", type=str, default=None)
    parser.add_argument("--data-dir", type=str, default=None,
                        help="dataset directory (required for --text-data)")
    parser.add_argument(
        "--text-data", action="store_true",
        help="raw-text vertical, Llama-style: materialize (or --ingest) a "
        "TEXT-schema dataset under --data-dir, train a first-party "
        "byte-level BPE vocab on it (tpudl.data.bpe), tokenize into an "
        "ids dataset, and LoRA-fine-tune on that — text -> BPE ids -> "
        "fine-tune in one command",
    )
    parser.add_argument("--ingest", type=str, default=None,
                        help="REAL GLUE SST-2 TSV (train.tsv or the SST-2 "
                        "directory) as the raw-text source")
    parser.add_argument("--materialize", action="store_true",
                        help="force re-materialization/re-tokenization of "
                        "--data-dir")
    parser.add_argument("--dtype", type=str, default="f32",
                        choices=["f32", "bf16"],
                        help="compute dtype (bf16 for real-scale runs; "
                        "f32 default keeps the tiny-model CI exact)")
    parser.add_argument("--attn", type=str, default="reference",
                        choices=["reference", "fused", "flash", "ring",
                                 "ulysses"],
                        help="attention implementation: 'fused'/'flash' "
                        "use the Pallas kernels (flash streams any length "
                        "with in-kernel dropout — the seq-2048 configs[4] "
                        "path); 'ring'/'ulysses' add sequence parallelism "
                        "over the sp mesh axis (both flash-bodied on TPU; "
                        "on one chip they degenerate to flash/reference)")
    parser.add_argument("--remat", action="store_true",
                        help="per-layer rematerialization (trade FLOPs "
                        "for HBM — how billion-param seq-2048 fits one "
                        "16G chip)")
    parser.add_argument(
        "--hf-checkpoint", type=str, default=None,
        help="local HuggingFace Llama checkpoint directory: base weights "
        "are grafted onto the model before LoRA fine-tuning (the actual "
        "configs[4] workload — pretrained, not random-init); adapters and "
        "the classifier head keep their fresh init",
    )
    args = parser.parse_args()
    if args.text_data and not args.data_dir:
        parser.error("--text-data requires --data-dir")
    if args.ingest and not args.text_data:
        parser.error("--ingest feeds the raw-text vertical: add --text-data")

    cfg = get_config("llama3_8b_lora", model=args.model)
    model = build_model(
        cfg.model, cfg.num_classes,
        dtype=jnp.bfloat16 if args.dtype == "bf16" else jnp.float32,
        attention_impl=args.attn,
        remat=args.remat,
    )

    sample = jnp.zeros((1, args.seq_len), jnp.int32)
    params = model.init(jax.random.key(cfg.seed), sample)["params"]
    if args.hf_checkpoint:
        import transformers

        from tpudl.models.llama import params_from_hf_llama

        hf = transformers.AutoModelForCausalLM.from_pretrained(
            args.hf_checkpoint, local_files_only=True
        )
        params = params_from_hf_llama(hf.state_dict(), like=params)
        print(f"grafted pretrained weights from {args.hf_checkpoint}")
    trainable, total = trainable_param_count(params, ("classifier",))
    print(f"{cfg.model}: {total/1e6:.1f}M params, "
          f"{trainable/1e6:.3f}M trainable ({100*trainable/total:.2f}%)")

    tx = lora_optimizer(make_optimizer(cfg.optim), params, ("classifier",))
    # Build the state directly from the already-initialized (possibly
    # HF-grafted) tree — create_train_state would run a second full init
    # only to throw it away (2x startup cost at 8B scale).
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    if args.mesh:
        mesh_spec = MeshSpec(*(int(x) for x in args.mesh.split(",")))
    else:
        mesh_spec = MeshSpec(dp=-1)
    mesh = make_mesh(mesh_spec)
    rules = compose_rules(LORA_RULES, TP_TRANSFORMER_RULES)
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh,
        state,
        rules,
    )

    warmup = min(2, args.steps)
    if args.text_data:
        import os

        from tpudl.data.bpe import ByteBPETokenizer, train_bpe
        from tpudl.data.converter import make_converter as _mk
        from tpudl.data.datasets import (
            materialize_sst2_text,
            normalize_sst2_batch,
            tokenize_text_dataset,
        )

        text_dir = os.path.join(args.data_dir, "text")
        ids_dir = os.path.join(args.data_dir, "ids")
        bpe_dir = os.path.join(args.data_dir, "bpe")
        if os.path.isdir(ids_dir) and not (args.materialize or args.ingest):
            # Petastorm contract: materialize once, train many.
            print(f"reusing tokenized dataset {ids_dir} (BPE {bpe_dir})")
            conv = _mk(ids_dir)
        else:
            if args.ingest:
                from tpudl.data.ingest import ingest_sst2_tsv

                text_conv = ingest_sst2_tsv(args.ingest, text_dir)
                print(f"ingested {args.ingest} -> {text_dir} "
                      f"({text_conv.num_rows} rows)")
            else:
                text_conv = materialize_sst2_text(text_dir, num_rows=8_192)
            corpus = (
                str(s)
                for b in text_conv.make_batch_iterator(
                    1024, epochs=1, shuffle=False, drop_last=False,
                    columns=("sentence",),
                )
                for s in b["sentence"]
            )
            tok = train_bpe(
                corpus, vocab_size=min(model.cfg.vocab_size, 4096)
            )
            tok.save(bpe_dir)
            print(f"trained byte-level BPE ({len(tok.vocab)} tokens, "
                  f"{len(tok.merges)} merges) -> {bpe_dir}")
            conv = tokenize_text_dataset(
                text_dir, ids_dir, tok, seq_len=args.seq_len
            )
        batches = (
            normalize_sst2_batch(b)
            for b in conv.make_batch_iterator(
                args.batch, epochs=None, shuffle=True, seed=cfg.seed
            )
        )
    else:
        batches = synthetic_token_batches(
            args.batch,
            seq_len=args.seq_len,
            vocab_size=model.cfg.vocab_size,
            num_classes=cfg.num_classes,
            seed=cfg.seed,
            num_batches=args.steps + warmup,
        )
    logger = MetricLogger(args.log_dir) if args.log_dir else None
    rng = jax.random.key(cfg.seed + 1)
    # Warmup fit absorbs compile so the throughput print is steady-state
    # (the repo-wide timing doctrine — bench.py). islice hands fit exactly
    # `warmup` items: fit's own num_steps break would pull (and discard)
    # one extra batch from the shared generator.
    state, _, _ = fit(step, state, itertools.islice(batches, warmup), rng)
    state, metrics, info = fit(
        step,
        state,
        batches,
        rng,
        num_steps=args.steps,
        log_every=20,
        logger=logger,
    )
    if logger:
        logger.close()
    print(f"final: {metrics}")
    print(f"{args.batch * info['steps'] / info['seconds']:.1f} samples/sec "
          f"over {info['steps']} steady-state steps (compile excluded) on "
          f"mesh {dict(mesh.shape)}")


if __name__ == "__main__":
    main()
