"""Llama LoRA fine-tune (BASELINE.json configs[4], the GSPMD stretch).

The reference declares this workload only through the driver north-star
(nothing exists in the reference tree — SURVEY.md §0). TPU-native shape:
a Llama decoder with rank-r adapters (tpudl.models.lora), frozen base via
optax.multi_transform (no optimizer moments for frozen weights — the
memory win that fits 8B), sharded by composed LORA+TP+FSDP rules over the
(dp, fsdp, sp, tp) mesh, classification from the last non-pad token.

Defaults run the tiny model so the script executes anywhere (including
the 8-device fake CPU mesh); pass --model llama3-8b-lora on a pod slice.

Run: python notebooks/nlp/finetune_lora.py [--steps N] [--model llama-tiny-lora]
"""

import argparse
import itertools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.synthetic import synthetic_token_batches
from tpudl.models.lora import (
    LORA_RULES,
    compose_rules,
    lora_optimizer,
    trainable_param_count,
)
from tpudl.models.registry import build_model
from tpudl.parallel.sharding import TP_TRANSFORMER_RULES
from tpudl.runtime import MeshSpec, make_mesh
from tpudl.train import (
    MetricLogger,
    TrainState,
    compile_step,
    fit,
    make_classification_train_step,
)
from tpudl.train.optim import make_optimizer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--model", type=str, default="llama-tiny-lora")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--mesh", type=str, default=None,
                        help="dp,fsdp,sp,tp (e.g. 2,2,1,2); default all-dp")
    parser.add_argument("--log-dir", type=str, default=None)
    parser.add_argument(
        "--hf-checkpoint", type=str, default=None,
        help="local HuggingFace Llama checkpoint directory: base weights "
        "are grafted onto the model before LoRA fine-tuning (the actual "
        "configs[4] workload — pretrained, not random-init); adapters and "
        "the classifier head keep their fresh init",
    )
    args = parser.parse_args()

    cfg = get_config("llama3_8b_lora", model=args.model)
    model = build_model(cfg.model, cfg.num_classes, dtype=jnp.float32)

    sample = jnp.zeros((1, args.seq_len), jnp.int32)
    params = model.init(jax.random.key(cfg.seed), sample)["params"]
    if args.hf_checkpoint:
        import transformers

        from tpudl.models.llama import params_from_hf_llama

        hf = transformers.AutoModelForCausalLM.from_pretrained(
            args.hf_checkpoint, local_files_only=True
        )
        params = params_from_hf_llama(hf.state_dict(), like=params)
        print(f"grafted pretrained weights from {args.hf_checkpoint}")
    trainable, total = trainable_param_count(params, ("classifier",))
    print(f"{cfg.model}: {total/1e6:.1f}M params, "
          f"{trainable/1e6:.3f}M trainable ({100*trainable/total:.2f}%)")

    tx = lora_optimizer(make_optimizer(cfg.optim), params, ("classifier",))
    # Build the state directly from the already-initialized (possibly
    # HF-grafted) tree — create_train_state would run a second full init
    # only to throw it away (2x startup cost at 8B scale).
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    if args.mesh:
        mesh_spec = MeshSpec(*(int(x) for x in args.mesh.split(",")))
    else:
        mesh_spec = MeshSpec(dp=-1)
    mesh = make_mesh(mesh_spec)
    rules = compose_rules(LORA_RULES, TP_TRANSFORMER_RULES)
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh,
        state,
        rules,
    )

    warmup = min(2, args.steps)
    batches = synthetic_token_batches(
        args.batch,
        seq_len=args.seq_len,
        vocab_size=model.cfg.vocab_size,
        num_classes=cfg.num_classes,
        seed=cfg.seed,
        num_batches=args.steps + warmup,
    )
    logger = MetricLogger(args.log_dir) if args.log_dir else None
    rng = jax.random.key(cfg.seed + 1)
    # Warmup fit absorbs compile so the throughput print is steady-state
    # (the repo-wide timing doctrine — bench.py). islice hands fit exactly
    # `warmup` items: fit's own num_steps break would pull (and discard)
    # one extra batch from the shared generator.
    state, _, _ = fit(step, state, itertools.islice(batches, warmup), rng)
    state, metrics, info = fit(
        step,
        state,
        batches,
        rng,
        num_steps=args.steps,
        log_every=20,
        logger=logger,
    )
    if logger:
        logger.close()
    print(f"final: {metrics}")
    print(f"{args.batch * info['steps'] / info['seconds']:.1f} samples/sec "
          f"over {info['steps']} steady-state steps (compile excluded) on "
          f"mesh {dict(mesh.shape)}")


if __name__ == "__main__":
    main()
