"""ResNet-18 / CIFAR-10 training smoke (BASELINE.json configs[0]).

The CV training workload the reference lineage runs through
HorovodRunner/Lightning on GPU clusters, as a single-process TPU run.
--data-dir points at a CIFAR-schema Parquet dataset fed through the
converter layer (pass --materialize to generate a synthetic one there
first); without it, an in-memory synthetic stream is used.

Run: python notebooks/cv/train_cifar10.py [--steps N]
"""

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models.registry import build_model
from tpudl.runtime import make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    fit,
    make_classification_train_step,
)
from tpudl.train.optim import make_optimizer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--data-dir", type=str, default=None,
                        help="CIFAR-schema Parquet dataset directory")
    parser.add_argument("--materialize", action="store_true",
                        help="generate a synthetic dataset into --data-dir first")
    args = parser.parse_args()
    if args.materialize and not args.data_dir:
        parser.error("--materialize requires --data-dir")

    cfg = get_config("cifar10_resnet18")
    batch_size = args.batch or cfg.global_batch_size

    model = build_model(cfg.model, cfg.num_classes, small_inputs=True)
    state = create_train_state(
        jax.random.key(cfg.seed),
        model,
        jnp.zeros((1, cfg.image_size, cfg.image_size, 3)),
        make_optimizer(cfg.optim),
    )
    mesh = make_mesh(cfg.mesh)
    step = compile_step(
        make_classification_train_step(cfg.label_smoothing), mesh, state, None
    )

    warmup_steps = 2
    if args.data_dir:
        from tpudl.data.augment import BatchAugmenter
        from tpudl.data.converter import make_converter
        from tpudl.data.datasets import materialize_cifar10_like

        if args.materialize:
            conv = materialize_cifar10_like(args.data_dir, num_rows=50_000)
        else:
            conv = make_converter(args.data_dir)
        # Standard CIFAR training augmentation (pad-4 random crop + flip +
        # normalize), fused in the native C++ kernel when available
        # (tpudl/native/augment.cpp; numpy fallback otherwise).
        augment = BatchAugmenter(
            crop=(cfg.image_size, cfg.image_size), pad=4, seed=cfg.seed
        )
        raw = conv.make_batch_iterator(
            batch_size, epochs=None, shuffle=True, seed=cfg.seed,
            transform=augment,
        )
    else:
        raw = synthetic_classification_batches(
            batch_size,
            image_shape=(cfg.image_size, cfg.image_size, 3),
            num_classes=cfg.num_classes,
            seed=cfg.seed,
            num_batches=args.steps + warmup_steps,
        )
    # Prefetch either stream: explicit placement overlaps the host->device
    # transfer with compute (jit's implicit numpy-arg transfer is
    # pathologically slow on relay-attached devices).
    from tpudl.data.converter import prefetch_to_device

    batches = prefetch_to_device(raw, mesh=mesh)
    rng = jax.random.key(cfg.seed + 1)

    def log(i, metrics):
        print(f"step {i}: loss {metrics['loss']:.4f} acc {metrics['accuracy']:.3f}")

    # Warmup outside the timing window, closed by a readback (compile is
    # synchronous, but program upload + first execution on the relay-
    # attached chip is async behind the dispatch).
    batches = iter(batches)
    for _ in range(warmup_steps):
        state, warm = step(state, next(batches), rng)
    float(warm["loss"])
    state, metrics, info = fit(
        step, state, batches, rng, num_steps=args.steps,
        log_every=cfg.log_every, logger=log,
    )
    print(f"final: {metrics}")
    print(
        f"throughput ~{batch_size * info['steps'] / info['seconds']:.0f} images/sec "
        f"over {info['steps']} steady-state steps (compile + warmup excluded)"
    )


if __name__ == "__main__":
    main()
