"""CV training workloads (BASELINE.json configs[0] and configs[2]).

The CV training the reference lineage runs through HorovodRunner/Lightning
on GPU clusters, as config-driven TPU runs:

  python notebooks/cv/train_cifar10.py                                # configs[0]
  python notebooks/cv/train_cifar10.py --config imagenet_resnet50_dp  # configs[2]

--config selects the BASELINE.json entry: model, dataset schema +
materializer, mesh, strategy, optimizer, label smoothing, and gradient
accumulation all come from tpudl.config. The declared mesh auto-clamps to
the local device count (MeshSpec.fit), so the same command drives one
chip or a pod slice. configs[2]'s declared global batch 1024 is realized
on a single 16G chip via accum_steps (microbatches scanned inside the
compiled step — tpudl.train.loop.microbatch).

--data-dir points at a Parquet dataset in the config's schema, fed
through the converter layer (pass --materialize to generate a synthetic
one there first); without it, an in-memory synthetic stream is used.

L5 composition (SURVEY.md §5.3-§5.5): --checkpoint-dir saves/RESUMES
through tpudl.checkpoint.CheckpointManager (kill the run, rerun the same
command, training continues), --log-dir streams metrics through
MetricLogger (JSONL + TensorBoard), and a held-out eval (true holdout —
last Parquet file, or the last rows of a single-file dataset) prints
final accuracy — the reference verifies model outputs every run
(reference notebooks/cv/onnx_experiments.py:98-100,178-184); so does
this.
"""

import argparse
import itertools
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.converter import make_converter, prefetch_to_device
from tpudl.data.datasets import eval_stream, split_train_eval
from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models.registry import build_model
from tpudl.parallel.sharding import strategy_rules
from tpudl.runtime import apply_platform_env, make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    evaluate,
    fit,
    make_classification_eval_step,
    make_classification_train_step,
)
from tpudl.train.metrics import compiled_flops, device_peak_flops, mfu
from tpudl.train.optim import make_optimizer

apply_platform_env()

#: CV configs this driver accepts, with their dataset materializers.
CV_CONFIGS = ("cifar10_resnet18", "imagenet_resnet50_dp")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, default="cifar10_resnet18",
                        choices=CV_CONFIGS,
                        help="BASELINE.json config to drive")
    parser.add_argument("--steps", type=int, default=200,
                        help="total optimizer-step budget (warmup included)")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--accum", type=int, default=None,
                        help="gradient-accumulation microbatches "
                        "(default: config accum_steps)")
    parser.add_argument("--data-dir", type=str, default=None,
                        help="Parquet dataset directory (config schema)")
    parser.add_argument("--materialize", action="store_true",
                        help="generate a synthetic dataset into --data-dir first")
    parser.add_argument("--ingest", type=str, default=None,
                        help="REAL dataset to ingest into --data-dir "
                        "Parquet before training (tpudl.data.ingest): the "
                        "CIFAR-10 python archive (cifar-10-python.tar.gz "
                        "or its extracted directory) for cifar10 configs, "
                        "or a class-subdirectory JPEG/PNG tree (ImageNet "
                        "train/ layout) for imagenet-shape configs")
    parser.add_argument("--rows", type=int, default=None,
                        help="rows to materialize (default: dataset-specific)")
    parser.add_argument("--strategy", type=str, default=None,
                        help="override config strategy: dp | fsdp")
    parser.add_argument("--checkpoint-dir", type=str, default=None,
                        help="CheckpointManager directory: saves every "
                        "--checkpoint-every steps and RESUMES from the "
                        "latest checkpoint on restart")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    parser.add_argument("--log-dir", type=str, default=None,
                        help="MetricLogger directory (JSONL + TensorBoard)")
    parser.add_argument("--eval-steps", type=int, default=8,
                        help="held-out eval batches after training (0 = off)")
    parser.add_argument("--mfu-compiled", action="store_true",
                        help="exact compiled-cost FLOPs for an MFU print "
                        "(pays a second full XLA compile at the end)")
    args = parser.parse_args()
    if (args.materialize or args.ingest) and not args.data_dir:
        parser.error("--materialize/--ingest require --data-dir")
    if args.ingest and not os.path.exists(args.ingest):
        parser.error(f"--ingest path does not exist: {args.ingest}")

    overrides = {}
    if args.strategy:
        overrides["strategy"] = args.strategy
    if args.checkpoint_dir:
        overrides["checkpoint_dir"] = args.checkpoint_dir
    cfg = get_config(args.config, **overrides)
    batch_size = args.batch or cfg.global_batch_size
    accum = args.accum if args.accum is not None else cfg.accum_steps
    is_cifar = cfg.dataset == "cifar10"

    mesh_spec = cfg.mesh.fit(jax.device_count())
    mesh = make_mesh(mesh_spec)
    model = build_model(cfg.model, cfg.num_classes, small_inputs=is_cifar)
    state = create_train_state(
        jax.random.key(cfg.seed),
        model,
        jnp.zeros((1, cfg.image_size, cfg.image_size, 3)),
        make_optimizer(cfg.optim),
    )
    num_params = sum(p.size for p in jax.tree.leaves(state.params))
    print(f"{cfg.name}: {cfg.model} {num_params / 1e6:.1f}M params, "
          f"batch {batch_size} (accum {accum}), image {cfg.image_size}, "
          f"strategy {cfg.strategy}, mesh {dict(mesh.shape)}")
    rules = strategy_rules(cfg.strategy)
    # Parquet-fed runs ship uint8 over the host->device link and
    # normalize ON DEVICE (fused into the first conv) — 4x less transfer
    # (tpudl.data.augment.device_normalize). The synthetic stream is
    # already f32.
    from tpudl.data.augment import (
        CIFAR10_MEAN,
        CIFAR10_STD,
        IMAGENET_MEAN,
        IMAGENET_STD,
        device_normalize,
    )

    norm_mean = CIFAR10_MEAN if is_cifar else IMAGENET_MEAN
    norm_std = CIFAR10_STD if is_cifar else IMAGENET_STD
    input_transform = (
        device_normalize(norm_mean, norm_std) if args.data_dir else None
    )
    step = compile_step(
        make_classification_train_step(
            cfg.label_smoothing, accum_steps=accum,
            input_transform=input_transform,
        ),
        mesh, state, rules,
    )

    warmup_steps = 2
    if args.data_dir:
        from tpudl.data.augment import BatchAugmenter
        from tpudl.data.datasets import (
            materialize_cifar10_like,
            materialize_imagenet_like,
        )

        if args.ingest:
            from tpudl.data.ingest import ingest_cifar10, ingest_image_folder

            if is_cifar:
                conv = ingest_cifar10(args.ingest, args.data_dir)
            else:
                conv = ingest_image_folder(
                    args.ingest, args.data_dir, image_size=cfg.image_size,
                )
            print(f"ingested {args.ingest} -> {args.data_dir} "
                  f"({conv.num_rows} rows)")
        elif args.materialize:
            if is_cifar:
                conv = materialize_cifar10_like(
                    args.data_dir, num_rows=args.rows or 50_000
                )
            else:
                conv = materialize_imagenet_like(
                    args.data_dir, num_rows=args.rows or 8_192,
                    image_size=cfg.image_size, num_classes=cfg.num_classes,
                )
        else:
            conv = make_converter(args.data_dir)
        train_conv, eval_conv = split_train_eval(conv)
        # Standard training augmentation (pad+random crop + flip) in
        # uint8 on the host; normalization happens on device
        # (input_transform above).
        augment = BatchAugmenter(
            crop=(cfg.image_size, cfg.image_size),
            pad=4 if is_cifar else 8, seed=cfg.seed,
            mean=norm_mean, std=norm_std, normalize=False,
        )
        # Augmentation is passed to the PREFETCHER (below), not the
        # converter: converter transforms run inside the source lock,
        # one at a time; the prefetcher's assembly pool crops/flips N
        # batches in parallel.
        raw = train_conv.make_batch_iterator(
            batch_size, epochs=None, shuffle=True, seed=cfg.seed,
        )
        host_transform = augment

        # Eval path: SAME device normalization, center crop, no flip.
        eval_augment = BatchAugmenter(
            crop=(cfg.image_size, cfg.image_size), pad=0, hflip=False,
            train=False, mean=norm_mean, std=norm_std, normalize=False,
        )

        def _eval_normalize(b):
            out = eval_augment(b)
            out["label"] = out["label"].astype("int32")
            return out

        eval_raw = eval_stream(
            eval_conv, batch_size, _eval_normalize,
            batch_divisor=mesh.shape["dp"] * mesh.shape["fsdp"],
        )
    else:
        host_transform = None  # synthetic stream is already f32
        raw = synthetic_classification_batches(
            batch_size,
            image_shape=(cfg.image_size, cfg.image_size, 3),
            num_classes=cfg.num_classes,
            seed=cfg.seed,
            num_batches=args.steps + warmup_steps,
        )

        def eval_raw():
            # Held-out synthetic stream: same distribution, disjoint seed.
            return synthetic_classification_batches(
                batch_size,
                image_shape=(cfg.image_size, cfg.image_size, 3),
                num_classes=cfg.num_classes,
                seed=cfg.seed + 10_000,
                num_batches=args.eval_steps,
            )

    # Checkpoint/resume: restore the latest state if the directory has
    # one; fast-forward the stream so a killed run rerun with the same
    # flags continues where it stopped.
    ckpt_mgr = None
    start_step = 0
    if cfg.checkpoint_dir:
        from tpudl.checkpoint import CheckpointManager
        from tpudl.train import resume_latest

        ckpt_mgr = CheckpointManager(cfg.checkpoint_dir)
        state, start_step = resume_latest(ckpt_mgr, state, mesh, rules)
        if start_step:
            print(f"resumed from step {start_step} ({cfg.checkpoint_dir})")

    # Prefetch either stream: explicit placement overlaps the host->device
    # transfer with compute (jit's implicit numpy-arg transfer is
    # pathologically slow on relay-attached devices). Parquet-fed runs
    # get an assembly pool (row-group decode + uint8 augmentation
    # parallelize host-side); the in-memory synthetic stream needs none.
    # Depth autotunes off the data-wait p95 (TPUDL_PREFETCH_DEPTH pins).
    # Fast-forward a resumed run on the HOST side (before device
    # prefetch) so skipped batches never pay a transfer.
    if start_step:
        raw = itertools.islice(iter(raw), start_step, None)
    batches = iter(
        prefetch_to_device(
            raw, mesh=mesh, transform=host_transform,
            assembly_workers=4 if host_transform is not None else 1,
        )
    )
    rng = jax.random.key(cfg.seed + 1)

    logger = None
    if args.log_dir:
        from tpudl.train import MetricLogger

        logger = MetricLogger(args.log_dir)

    def log(i, metrics):
        print(f"step {i}: loss {metrics['loss']:.4f} acc {metrics['accuracy']:.3f}")
        if logger:
            logger(start_step + i, metrics)

    # Warmup outside the timing window, closed by a readback (compile is
    # synchronous, but program upload + first execution on the relay-
    # attached chip is async behind the dispatch).
    # --steps is the TOTAL optimizer-step budget (warmup included); a run
    # resumed at or past the budget trains zero further steps.
    budget = max(args.steps - start_step, 0)
    wsteps = min(warmup_steps, budget)
    remaining = budget - wsteps
    warm = None
    for _ in range(wsteps):
        state, warm = step(state, next(batches), rng)
    if warm is not None:
        float(warm["loss"])
    state, metrics, info = fit(
        step, state, itertools.islice(batches, remaining), rng,
        log_every=cfg.log_every, logger=log,
        checkpoint_manager=ckpt_mgr,
        checkpoint_every=args.checkpoint_every if ckpt_mgr else 0,
    )
    print(f"final: {metrics}")

    if args.eval_steps:
        eval_step = compile_step(
            make_classification_eval_step(input_transform=input_transform),
            mesh, state, rules, has_rng=False
        )
        eval_metrics = evaluate(
            eval_step, state, eval_raw(), num_steps=args.eval_steps
        )
        print(
            f"held-out eval (<= {args.eval_steps} batches): "
            f"loss {eval_metrics['loss']:.4f} "
            f"accuracy {eval_metrics['accuracy']:.3f}"
        )
        if logger:
            logger(start_step + info["steps"],
                   {f"eval_{k}": v for k, v in eval_metrics.items()})
    if logger:
        logger.close()
    if info["steps"] == 0:
        from tpudl.train import finalize_zero_step_run

        print(finalize_zero_step_run(ckpt_mgr, state, wsteps))
        return
    images_per_sec = batch_size * info["steps"] / max(info["seconds"], 1e-9)
    line = (
        f"throughput ~{images_per_sec:.0f} images/sec over {info['steps']} "
        f"steady-state steps (compile + warmup excluded)"
    )
    # MFU from the compiled executable's FLOPs (SURVEY.md §5.5) — opt-in:
    # lower().compile() pays a SECOND full XLA compile.
    if args.mfu_compiled:
        try:
            example = next(synthetic_classification_batches(
                batch_size, image_shape=(cfg.image_size, cfg.image_size, 3),
                num_classes=cfg.num_classes, num_batches=1,
            ))
            if input_transform is not None:
                # Parquet-fed runs train on uint8-wire batches; the
                # lowered example must match or the FLOPs describe a
                # program that never ran.
                example = dict(
                    example,
                    image=(example["image"] * 255).clip(0, 255).astype(
                        "uint8"
                    ),
                )
            flops = compiled_flops(step.jitted.lower(state, example, rng))
            if flops:
                step_seconds = info["seconds"] / max(info["steps"], 1)
                line += (
                    f"; MFU ~{100 * mfu(flops, step_seconds, jax.device_count()):.1f}%"
                    f" (peak {device_peak_flops() / 1e12:.0f} TFLOP/s/chip)"
                )
        except Exception:
            pass
    print(line)


if __name__ == "__main__":
    main()
