"""ResNet-18 / CIFAR-10 training smoke (BASELINE.json configs[0]).

The CV training workload the reference lineage runs through
HorovodRunner/Lightning on GPU clusters, as a single-process TPU run.
--data-dir points at a CIFAR-schema Parquet dataset fed through the
converter layer (pass --materialize to generate a synthetic one there
first); without it, an in-memory synthetic stream is used.

L5 composition (SURVEY.md §5.3-§5.5): --checkpoint-dir saves/RESUMES
through tpudl.checkpoint.CheckpointManager (kill the run, rerun the same
command, training continues), --log-dir streams metrics through
MetricLogger (JSONL + TensorBoard), and a held-out eval (last Parquet
file, a true holdout) prints final accuracy — the reference verifies
model outputs every run (reference notebooks/cv/onnx_experiments.py:
98-100,178-184); so does this.

Run: python notebooks/cv/train_cifar10.py [--steps N]
"""

import argparse
import itertools
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from tpudl.config import get_config
from tpudl.data.converter import make_converter, prefetch_to_device
from tpudl.data.datasets import eval_stream, split_train_eval
from tpudl.data.synthetic import synthetic_classification_batches
from tpudl.models.registry import build_model
from tpudl.parallel.sharding import strategy_rules
from tpudl.runtime import make_mesh
from tpudl.train import (
    compile_step,
    create_train_state,
    evaluate,
    fit,
    make_classification_eval_step,
    make_classification_train_step,
)
from tpudl.train.optim import make_optimizer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200,
                        help="total optimizer-step budget (warmup included)")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--data-dir", type=str, default=None,
                        help="CIFAR-schema Parquet dataset directory")
    parser.add_argument("--materialize", action="store_true",
                        help="generate a synthetic dataset into --data-dir first")
    parser.add_argument("--strategy", type=str, default=None,
                        help="override config strategy: dp | fsdp")
    parser.add_argument("--checkpoint-dir", type=str, default=None,
                        help="CheckpointManager directory: saves every "
                        "--checkpoint-every steps and RESUMES from the "
                        "latest checkpoint on restart")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    parser.add_argument("--log-dir", type=str, default=None,
                        help="MetricLogger directory (JSONL + TensorBoard)")
    parser.add_argument("--eval-steps", type=int, default=8,
                        help="held-out eval batches after training (0 = off)")
    args = parser.parse_args()
    if args.materialize and not args.data_dir:
        parser.error("--materialize requires --data-dir")

    overrides = {}
    if args.strategy:
        overrides["strategy"] = args.strategy
    if args.checkpoint_dir:
        overrides["checkpoint_dir"] = args.checkpoint_dir
    cfg = get_config("cifar10_resnet18", **overrides)
    batch_size = args.batch or cfg.global_batch_size

    model = build_model(cfg.model, cfg.num_classes, small_inputs=True)
    state = create_train_state(
        jax.random.key(cfg.seed),
        model,
        jnp.zeros((1, cfg.image_size, cfg.image_size, 3)),
        make_optimizer(cfg.optim),
    )
    mesh = make_mesh(cfg.mesh)
    rules = strategy_rules(cfg.strategy)
    step = compile_step(
        make_classification_train_step(cfg.label_smoothing), mesh, state, rules
    )

    warmup_steps = 2
    if args.data_dir:
        from tpudl.data.augment import BatchAugmenter
        from tpudl.data.datasets import materialize_cifar10_like

        if args.materialize:
            conv = materialize_cifar10_like(args.data_dir, num_rows=50_000)
        else:
            conv = make_converter(args.data_dir)
        train_conv, eval_conv = split_train_eval(conv)
        # Standard CIFAR training augmentation (pad-4 random crop + flip +
        # normalize), fused in the native C++ kernel when available
        # (tpudl/native/augment.cpp; numpy fallback otherwise).
        augment = BatchAugmenter(
            crop=(cfg.image_size, cfg.image_size), pad=4, seed=cfg.seed
        )
        raw = train_conv.make_batch_iterator(
            batch_size, epochs=None, shuffle=True, seed=cfg.seed,
            transform=augment,
        )

        # Eval path: SAME normalization as training (CIFAR mean/std via
        # the augmenter's eval mode), no crop/flip.
        eval_augment = BatchAugmenter(
            crop=(cfg.image_size, cfg.image_size), pad=0, hflip=False,
            train=False,
        )

        def _eval_normalize(b):
            out = eval_augment(b)
            out["label"] = out["label"].astype("int32")
            return out

        eval_raw = eval_stream(eval_conv, batch_size, _eval_normalize)
    else:
        raw = synthetic_classification_batches(
            batch_size,
            image_shape=(cfg.image_size, cfg.image_size, 3),
            num_classes=cfg.num_classes,
            seed=cfg.seed,
            num_batches=args.steps + warmup_steps,
        )

        def eval_raw():
            # Held-out synthetic stream: same distribution, disjoint seed.
            return synthetic_classification_batches(
                batch_size,
                image_shape=(cfg.image_size, cfg.image_size, 3),
                num_classes=cfg.num_classes,
                seed=cfg.seed + 10_000,
                num_batches=args.eval_steps,
            )

    # Checkpoint/resume: restore the latest state if the directory has
    # one; fast-forward the stream so a killed run rerun with the same
    # flags continues where it stopped.
    ckpt_mgr = None
    start_step = 0
    if cfg.checkpoint_dir:
        from tpudl.checkpoint import CheckpointManager
        from tpudl.train import resume_latest

        ckpt_mgr = CheckpointManager(cfg.checkpoint_dir)
        state, start_step = resume_latest(ckpt_mgr, state, mesh, rules)
        if start_step:
            print(f"resumed from step {start_step} ({cfg.checkpoint_dir})")

    # Prefetch either stream: explicit placement overlaps the host->device
    # transfer with compute (jit's implicit numpy-arg transfer is
    # pathologically slow on relay-attached devices).
    # Fast-forward a resumed run on the HOST side (before device
    # prefetch) so skipped batches never pay a transfer.
    if start_step:
        raw = itertools.islice(iter(raw), start_step, None)
    batches = iter(prefetch_to_device(raw, mesh=mesh))
    rng = jax.random.key(cfg.seed + 1)

    logger = None
    if args.log_dir:
        from tpudl.train import MetricLogger

        logger = MetricLogger(args.log_dir)

    def log(i, metrics):
        print(f"step {i}: loss {metrics['loss']:.4f} acc {metrics['accuracy']:.3f}")
        if logger:
            logger(start_step + i, metrics)

    # Warmup outside the timing window, closed by a readback (compile is
    # synchronous, but program upload + first execution on the relay-
    # attached chip is async behind the dispatch).
    # --steps is the TOTAL optimizer-step budget (warmup included); a run
    # resumed at or past the budget trains zero further steps.
    budget = max(args.steps - start_step, 0)
    wsteps = min(warmup_steps, budget)
    remaining = budget - wsteps
    warm = None
    for _ in range(wsteps):
        state, warm = step(state, next(batches), rng)
    if warm is not None:
        float(warm["loss"])
    state, metrics, info = fit(
        step, state, itertools.islice(batches, remaining), rng,
        log_every=cfg.log_every, logger=log,
        checkpoint_manager=ckpt_mgr,
        checkpoint_every=args.checkpoint_every if ckpt_mgr else 0,
    )
    print(f"final: {metrics}")

    if args.eval_steps:
        eval_step = compile_step(
            make_classification_eval_step(), mesh, state, rules, has_rng=False
        )
        eval_metrics = evaluate(
            eval_step, state, eval_raw(), num_steps=args.eval_steps
        )
        print(
            f"held-out eval (<= {args.eval_steps} batches): "
            f"loss {eval_metrics['loss']:.4f} "
            f"accuracy {eval_metrics['accuracy']:.3f}"
        )
        if logger:
            logger(start_step + info["steps"],
                   {f"eval_{k}": v for k, v in eval_metrics.items()})
    if logger:
        logger.close()
    print(
        f"throughput ~{batch_size * info['steps'] / info['seconds']:.0f} images/sec "
        f"over {info['steps']} steady-state steps (compile + warmup excluded)"
    )


if __name__ == "__main__":
    main()
