"""ResNet-50 export, cross-backend inference, parity, and latency.

TPU-native re-design of the reference notebook
`notebooks/cv/onnx_experiments.py` (its whole file — SURVEY.md §3.1-3.5),
with each step mapped:

  reference (torch/ONNX/OpenVINO, CPU/GPU)      this script (JAX/XLA, CPU/TPU)
  -------------------------------------------   ------------------------------
  models.resnet50(pretrained=True)     (:19)    tpudl Flax ResNet-50 (random
                                                init: zero-egress environment)
  torch.onnx.export, opset 12       (:33-42)    jax.export -> StableHLO bytes
  ORT InferenceSession + run        (:77-104)   load_exported(...) on CPU-XLA
  OpenVINO compile_model + infer   (:114-140)   the same artifact on TPU-XLA
  np.allclose(rtol=1e-5, atol=1e-4)(:142-144)   check_parity strict harness
  latency means over Python lists  (:90-104)    latency_benchmark (warmup,
                                                transfer/compute split, p50/95)
  torch.save / jit.trace + ls     (:194-219)    save_params + artifact_sizes

Run: python notebooks/cv/export_experiments.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np

from tpudl.export import (
    artifact_sizes,
    check_parity,
    export_stablehlo,
    latency_benchmark,
    load_exported,
    save_params,
)
from tpudl.models import ResNet50

# --- Model acquisition (reference :19). Random init: no weight downloads. ---
model = ResNet50(num_classes=1000, dtype=jnp.float32)
rng = jax.random.key(0)
sample = jnp.zeros((1, 224, 224, 3), jnp.float32)
variables = model.init(rng, sample, train=False)


def forward(images):
    return model.apply(variables, images, train=False)


# --- Preprocessing (reference :55-66): ImageNet normalization, NHWC. ---
MEAN = np.array([0.485, 0.456, 0.406], np.float32)
STD = np.array([0.229, 0.224, 0.225], np.float32)


def preprocess(image_uint8: np.ndarray) -> np.ndarray:
    x = image_uint8.astype(np.float32) / 255.0
    return ((x - MEAN) / STD)[None, ...]


image = np.random.default_rng(0).integers(0, 256, (224, 224, 3)).astype(np.uint8)
batch = preprocess(image)

# --- Export (reference :33-42): one artifact, multiple platforms. ---
blob = export_stablehlo(forward, (batch,), path="/tmp/resnet50.stablehlo",
                        platforms=("cpu", "tpu"))
print(f"exported StableHLO artifact: {len(blob)} bytes")

# --- Cross-backend inference from the artifact (reference :77-140). ---
restored = load_exported("/tmp/resnet50.stablehlo")
logits = np.asarray(restored(batch))
top5 = np.argsort(logits[0])[::-1][:5]
print("top-5 class indices:", top5.tolist())

# --- Numerical parity, CPU-XLA vs TPU-XLA (reference :142-144). ---
report = check_parity(forward, (batch,), strict=True)
print(report)
deploy_report = check_parity(forward, (batch,), strict=False)
print(deploy_report)

# --- Latency (reference :90-104,130-139), measurement flaws fixed. ---
for device in [jax.devices()[0], jax.devices("cpu")[0]]:
    result = latency_benchmark(forward, (batch,), device=device, warmup=3, iters=10)
    print(
        f"{result['device']}: compute p50 {result['compute']['p50_ms']:.2f} ms "
        f"(p95 {result['compute']['p95_ms']:.2f}), "
        f"transfer p50 {result['transfer']['p50_ms']:.2f} ms"
    )

# --- Artifact sizes (reference :194-219). ---
save_params("/tmp/resnet50_params", variables["params"])
sizes = artifact_sizes("/tmp/resnet50.stablehlo", "/tmp/resnet50_params")
for path, size in sizes.items():
    print(f"{path}: {size / 1e6:.1f} MB")
