"""Driver benchmark: one JSON line with the headline metrics.

BASELINE.json names two `metric` quantities; both are measured here on the
real chip, steady-state:

- BERT-base SST-2-shaped fine-tune samples/sec + MFU (the north-star
  acceptance is an MFU number, so it is first-class) — configs[1];
- ResNet-18 / CIFAR-10-shaped training images/sec/chip — configs[0]
  (continuity with the round-1 bank).

The reference publishes no numbers (`BASELINE.json` "published": {}), so
``vs_baseline`` compares against the values this repo banked in
BASELINE.md; a metric with no banked value reports 1.0 and its measurement
becomes the bank.

Timing protocol (see .claude/skills/verify/SKILL.md): the remote-TPU relay
makes `block_until_ready` unreliable for timing, so every window is closed
by a scalar host readback, and a warmup burst absorbs compile + relay
buffering.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import optax

from tpudl.runtime import use_hardware_rng

# Dropout-mask generation rides the TPU hardware RBG (+12% on the BERT
# fine-tune step vs the default threefry — tpudl/runtime/rng.py).
use_hardware_rng()

# Values banked in BASELINE.md (1x TPU v5 lite).
# Protocol correction (round 6, the BENCH_r05 0.923 investigation): the
# round-5 "best vs best" bank compared each round's SINGLE
# best-of-4-windows run against the MAX of four same-day
# best-of-4-windows runs (25.1k/29.9k/35.0k/36.9k -> 36.9k) — an
# order-statistic mismatch: one draw of a ±20% one-sided-noise metric
# almost never reaches the max of four draws, so the ratio reads < 1.0
# with no code change (the r05 bisect confirms: this bench feeds a
# synthetic device-resident batch and touches neither prefetch depth
# nor wire format). Corrected bank: the MEDIAN of those four
# same-protocol runs, so both sides of the ratio are single
# best-of-4-windows draws. The BERT metric's 170 ms steps hold ±1.5%
# and carry the headline; benchmarks/dispatch_overhead.py now tracks
# the dispatch stalls that make short-step metrics noisy in the first
# place.
BASELINE_RESNET_IMAGES_PER_SEC_BEST = 32_450.0
BASELINE_RESNET50_IMAGES_PER_SEC = 2482.6  # banked 2026-07-30 (round 2)
# Re-banked at batch 256 (round 2 close: 1320 samples/sec/chip) so
# vs_baseline is a like-for-like speedup at the same config — the old
# batch-32 bank (813) conflated a config change with optimization.
BASELINE_BERT_SAMPLES_PER_SEC = 1320.0

RESNET_BATCH = 256
RESNET_WARMUP_STEPS = 25
# ~9 ms/step. Relay-side jitter on short steps is ONE-SIDED (stalls,
# never speedups) and measured up to 35% spread between whole runs
# (24.3k..36.9k img/s same day, same code); the steady-state capability
# is the BEST of several windows, so measure RESNET_WINDOWS of
# RESNET_MEASURE_STEPS each and report the max.
RESNET_MEASURE_STEPS = 100
RESNET_WINDOWS = 4
RESNET50_BATCH = 128
RESNET50_WARMUP_STEPS = 10
# ~50 ms/step: 48 steps give a ~2.4 s window (16 measured 10% run-to-run
# noise through the relay).
RESNET50_MEASURE_STEPS = 48
# Batch 256 keeps the MXU fed: 32 -> 256 raised measured MFU 34% -> 49%
# (sweep 2026-07-30); dropout stays at the standard fine-tune 0.1.
BERT_BATCH = 256
BERT_SEQ = 128
BERT_WARMUP_STEPS = 15
BERT_MEASURE_STEPS = 30
# Fused-dispatch comparison width: 8 steps per compiled dispatch (the
# tentpole's default recommendation; benchmarks/dispatch_overhead.py
# sweeps other widths).
BERT_FUSED_K = 8


def _bench_resnet():
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.models import ResNet18
    from tpudl.runtime import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = ResNet18(num_classes=10, small_inputs=True)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 32, 32, 3)),
        optax.sgd(0.1, momentum=0.9),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)

    batch = next(
        synthetic_classification_batches(
            RESNET_BATCH, image_shape=(32, 32, 3), num_classes=10
        )
    )
    batch = jax.device_put(batch)
    rng = jax.random.key(1)

    for _ in range(RESNET_WARMUP_STEPS):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # close the warmup window with a readback

    best = float("inf")
    for _ in range(RESNET_WINDOWS):
        start = time.perf_counter()
        for _ in range(RESNET_MEASURE_STEPS):
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])
        best = min(best, time.perf_counter() - start)
    return RESNET_BATCH * RESNET_MEASURE_STEPS / best / jax.device_count()


def _bench_resnet50():
    """ResNet-50 at 224x224 — the BASELINE.json configs[2] headline shape
    (the reference's model: torchvision resnet50 at
    reference notebooks/cv/onnx_experiments.py:19,29-30)."""
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.models import ResNet50
    from tpudl.runtime import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = ResNet50(num_classes=1000)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 224, 224, 3)),
        optax.sgd(0.1, momentum=0.9),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)

    batch = next(
        synthetic_classification_batches(
            RESNET50_BATCH, image_shape=(224, 224, 3), num_classes=1000
        )
    )
    batch = jax.device_put(batch)
    rng = jax.random.key(1)

    for _ in range(RESNET50_WARMUP_STEPS):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])

    start = time.perf_counter()
    for _ in range(RESNET50_MEASURE_STEPS):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    return RESNET50_BATCH * RESNET50_MEASURE_STEPS / elapsed / jax.device_count()


def _bench_bert(fused_ops=False, warmup=None, measure=None,
                precision=None):
    """BERT-base fine-tune step: samples/sec/chip and MFU (compiled-cost
    FLOPs, 6ND transformer fallback).

    ``precision`` (a tpudl.train.precision preset name) measures the
    SAME workload under that mixed-precision policy — the ROADMAP
    item-6 training variant, reported as ``bert_base_mfu_bf16`` next
    to the headline. Lean step counts, and the fused-dispatch
    sub-bench is skipped (measured once, on the headline path).

    ``fused_ops=True`` measures the SAME workload with the fused
    epilogue tier on (Pallas LayerNorm+residual / bias+GeLU via
    ``BertConfig.fused_ops`` and the fused cross-entropy via
    ``loss_impl="auto"``) — the ROADMAP item-1 variant, reported as
    ``bert_base_mfu_fused_ops`` next to the headline until it earns the
    default. Lean step counts for the variant keep total bench runtime
    bounded."""
    from tpudl.data.synthetic import synthetic_token_batches
    from tpudl.models.registry import build_model
    from tpudl.runtime import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )
    from tpudl.train.metrics import (
        compiled_flops,
        device_peak_flops,
        mfu,
        transformer_train_flops,
    )

    from tpudl.config import get_config
    from tpudl.train.optim import make_optimizer

    # The real configs[1] optimizer stack (AdamW, bf16 first moment —
    # +2.6% step throughput, benchmarks/bert_mu_dtype.py) at a constant
    # LR so steady-state steps are identical.
    import dataclasses

    ocfg = dataclasses.replace(
        get_config("sst2_bert_base").optim, schedule="constant", warmup_steps=0
    )
    warmup = BERT_WARMUP_STEPS if warmup is None else warmup
    measure = BERT_MEASURE_STEPS if measure is None else measure
    model_kwargs = {"fused_ops": True} if fused_ops else {}
    model = build_model("bert-base", num_classes=2, **model_kwargs)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, BERT_SEQ), jnp.int32),
        make_optimizer(ocfg),
        precision=precision,
    )
    num_params = sum(p.size for p in jax.tree.leaves(state.params))
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label",
            loss_impl="auto" if fused_ops else "reference",
            precision=precision,
        ),
        mesh,
        state,
        None,
        precision=precision,
    )

    batch = next(
        synthetic_token_batches(BERT_BATCH, seq_len=BERT_SEQ, vocab_size=30_522)
    )
    # Explicit placement to the step's shardings, then ONE AOT compile
    # serves both the cost analysis (the compiled-cost MFU basis banked
    # since round 2) and the stepping — lowering separately for
    # cost_analysis would pay a duplicate multi-minute BERT compile.
    state = jax.device_put(state, step.state_shardings)
    batch = jax.device_put(batch, step.batch_sharding)
    rng = jax.device_put(
        jax.random.key(1),
        jax.sharding.NamedSharding(
            step.batch_sharding.mesh, jax.sharding.PartitionSpec()
        ),
    )
    # Lower under the active mesh: constrain() activation constraints
    # are trace-time thread-local no-ops otherwise, and this executable
    # is the one actually benchmarked (on one chip they clamp away; on a
    # real slice dropping them would benchmark a different program than
    # training runs).
    from tpudl.parallel.sharding import active_mesh

    with active_mesh(step.batch_sharding.mesh):
        compiled = step.jitted.lower(state, batch, rng).compile()
    flops = compiled_flops(compiled)
    if flops is None:
        flops = transformer_train_flops(num_params, BERT_BATCH * BERT_SEQ)
    step = compiled  # donation/shardings baked into the executable

    for _ in range(warmup):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])

    start = time.perf_counter()
    for _ in range(measure):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start

    step_seconds = elapsed / measure
    samples_per_sec = BERT_BATCH / step_seconds / jax.device_count()

    # Fused K-step dispatch (tpudl/train/loop.py steps_per_dispatch):
    # the same step scanned 8x inside ONE executable, so the per-step
    # host dispatch cost — the suspected driver of the three-round
    # 0.527-MFU plateau — is paid once per 8 steps. The headline metric
    # above stays the default single-dispatch path (the new path is off
    # by default); this delta quantifies what turning it on recovers.
    # Skipped for the fused-ops variant (measured once, on the headline
    # path).
    fused = {}
    try:
        if fused_ops or precision is not None:
            return samples_per_sec, mfu(
                flops, step_seconds, jax.device_count(),
                device_peak_flops(),
            ), fused
        from benchmarks.dispatch_overhead import (
            stack_window,
            time_fused_per_step,
        )

        step8 = compile_step(
            make_classification_train_step(
                input_keys=("input_ids", "attention_mask"),
                label_key="label",
            ),
            mesh,
            state,
            None,
            steps_per_dispatch=BERT_FUSED_K,
        )
        window = jax.device_put(
            stack_window(batch, BERT_FUSED_K), step8.window_sharding
        )
        fused_step_seconds, _ = time_fused_per_step(
            step8, state, window, rng, BERT_FUSED_K,
            warmup_dispatches=2, dispatches=4,
        )
        fused = {
            "step_dispatch_overhead_ms": round(
                (step_seconds - fused_step_seconds) * 1e3, 3
            ),
            "fused_dispatch_speedup": round(
                step_seconds / fused_step_seconds, 3
            ),
        }
    except Exception:
        import sys
        import traceback

        print("fused-dispatch bench failed:", file=sys.stderr)
        traceback.print_exc()

    return samples_per_sec, mfu(
        flops, step_seconds, jax.device_count(), device_peak_flops()
    ), fused


def _bench_bert_large():
    """BERT-large at configs[3]'s declared global batch 256 (4x64
    gradient-accumulation microbatches — the round-4 lever stack: bf16
    first moment, state donation, in-step accumulation; BASELINE.md).
    Lean step counts: this is the secondary metric."""
    import optax

    from tpudl.data.synthetic import synthetic_token_batches
    from tpudl.models.bert import BERT_LARGE, BertForSequenceClassification
    from tpudl.runtime import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )
    from tpudl.train.metrics import (
        device_peak_flops,
        mfu,
        transformer_train_flops,
    )

    batch, accum = 256, 4
    mesh = make_mesh(MeshSpec(dp=-1))
    model = BertForSequenceClassification(BERT_LARGE())
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, BERT_SEQ), jnp.int32),
        optax.adamw(2e-5, weight_decay=0.01, mu_dtype=jnp.bfloat16),
    )
    n_params = sum(p.size for p in jax.tree.leaves(state.params))
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label",
            accum_steps=accum,
        ),
        mesh,
        state,
        None,
    )
    data = jax.device_put(
        next(synthetic_token_batches(batch, seq_len=BERT_SEQ,
                                     vocab_size=30_522)),
        step.batch_sharding,
    )
    state = jax.device_put(state, step.state_shardings)
    rng = jax.device_put(
        jax.random.key(1),
        jax.sharding.NamedSharding(
            step.batch_sharding.mesh, jax.sharding.PartitionSpec()
        ),
    )
    flops = transformer_train_flops(n_params, batch * BERT_SEQ)
    # ONE AOT compile serves both the stepping and the compiled-cost MFU
    # basis (same pattern as _bench_bert — the step compiles exactly once
    # either way). cost_analysis counts the accumulation scan BODY once
    # (one batch/accum microbatch — XLA does not multiply loop trip
    # counts), so the true step cost is accum x the reported flops; the
    # ratio guard below catches a jax version changing that behavior
    # (BASELINE.md round-5 row: body/6ND-per-microbatch ratio is ~0.93).
    from tpudl.train.metrics import compiled_flops
    from tpudl.parallel.sharding import active_mesh

    with active_mesh(step.batch_sharding.mesh):
        compiled = step.jitted.lower(state, data, rng).compile()
    body_flops = compiled_flops(compiled)
    flops_compiled = None
    if body_flops is not None and 0.5 < body_flops / (flops / accum) < 1.1:
        flops_compiled = body_flops * accum
    step = compiled
    # Lean counts: each accumulated step is ~450 ms and very stable
    # (4 scanned microbatches average out per-step noise), and bench.py's
    # total runtime must stay comfortably inside the driver's window.
    for _ in range(4):
        state, m = step(state, data, rng)
    float(m["loss"])
    start = time.perf_counter()
    n = 6
    for _ in range(n):
        state, m = step(state, data, rng)
    float(m["loss"])
    dt = (time.perf_counter() - start) / n
    peak = device_peak_flops()
    return (
        batch / dt / jax.device_count(),
        mfu(flops, dt, jax.device_count(), peak),
        mfu(flops_compiled, dt, jax.device_count(), peak)
        if flops_compiled is not None
        else None,
    )


def _bench_input_pipeline():
    """Host input-pipeline feeding rate (images/sec delivered to the
    device, model-free — benchmarks/input_pipeline.py): the perf
    trajectory must capture the feeding rate, not just what the chips do
    with the batches (an input-bound model regresses here first)."""
    from benchmarks.input_pipeline import measure_both

    legacy, pipelined = measure_both(
        rows=8_192, batch_size=256, measure_batches=24
    )
    return legacy, pipelined


def _bench_serve():
    """Serving headline: continuous-batching tokens/sec, p99 TTFT, and
    the speedup over run-to-completion static batching at equal slots
    (benchmarks/serve_load.py — tiny-Llama engine, warmed up, ragged
    request mix)."""
    from benchmarks.serve_load import measure_serve

    return measure_serve(n_requests=16, num_slots=4)


def _bench_serve_replicas():
    """Multi-replica serving tier (benchmarks/serve_load.py): routed
    2-replica tokens/sec + scaling efficiency on the ragged mix
    (simulated per-step device latency — see the benchmark docstring)
    and resident slots per GB of the int8 paged KV cache. Banked by
    scripts/bench_regress.py from r06 onward (new keys enter the bank
    as no-baseline on their first round)."""
    from benchmarks.serve_load import measure_serve_replicas

    return measure_serve_replicas()


def _bench_fleet():
    """Fleet observability + autoscaling tier (benchmarks/
    serve_load.py): the scale-up-to-burn-clear recovery time of the
    SLO-driven autoscaler under 2x overload, and the FleetMonitor's
    per-cycle real-HTTP scrape overhead. Banked by
    scripts/bench_regress.py from r06 onward (lower is better for
    both)."""
    from benchmarks.serve_load import measure_fleet

    return measure_fleet()


def _bench_fleet_mesh():
    """Pod-real fleet tier (tpudl.fleet via benchmarks/fleet_mesh.py):
    elastic reshard-restore wall time (4-device checkpoint onto an
    8-device mesh), routed throughput over two 4-device MeshReplicas,
    and the chip mover's burn-to-cleared time for the full
    preempt -> shrink -> serve -> drain -> grow scenario. Runs as a
    subprocess: the forced host-device count must be set before jax
    imports, which this process has long since done."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.fleet_mesh", "--json"],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"fleet_mesh subprocess failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _bench_parity_grid():
    """Low-precision serving grid (benchmarks/parity_grid.py): every
    precision x backend cell parity-gated against the f32 reference,
    reporting the int8-weights simulated-device TPOT, the quantized
    weight-bytes ratio, and the number of cells that passed. Banked by
    scripts/bench_regress.py from r06 onward."""
    from benchmarks.parity_grid import measure_parity_grid

    return measure_parity_grid()


def _bench_prefix_spec():
    """Prefix-sharing + speculative-decoding tier (benchmarks/
    serve_load.py): p50 TTFT on the 50%-shared-prefix ragged mix with
    radix sharing on (asserted >= 2x under no-sharing inside the
    benchmark), accepted-tokens-per-step of the greedy int8 self-draft
    (asserted >= 2), and speculative tokens/sec on the simulated
    device (asserted above the non-speculative baseline). Banked from
    r07 onward (new keys enter as no-baseline on their first round)."""
    from benchmarks.serve_load import measure_prefix_spec

    return measure_prefix_spec()


def _bench_block_pins():
    """ROADMAP item-1 follow-through: run the fused-epilogue
    block-size sweep and record the winning env pins in the JSON tail,
    so a TPU round's evidence for flipping fused defaults is banked
    next to the metrics it would move. Off-TPU the sweep runs the tiny
    smoke shapes (interpret-mode Pallas) — plumbing-checkable, but the
    pins that matter come from the driver's TPU rounds."""
    from benchmarks.fused_epilogue import block_pins, sweep_args, sweep_blocks
    from tpudl.ops.attention import is_tpu_backend

    best = sweep_blocks(sweep_args(smoke=not is_tpu_backend()), measure=5)
    pins, command = block_pins(best)
    return {"per_family": best, "pins": pins, "command": command}


def _bench_tenants():
    """Multi-tenant LoRA serving tier (tpudl.serve.lora +
    tpudl.ops.segmented_lora via benchmarks/serve_load.py --tenants):
    resident adapters per GB of pool (byte-accurate arithmetic),
    heterogeneous batched decode tokens/sec at 64 resident adapters
    (asserted >= 2x over the sequential per-tenant-dispatch baseline
    inside the benchmark), and the tenant-isolation p99 TTFT ratio
    under one tenant's 4x overload (asserted <= 1.3x solo)."""
    from benchmarks.serve_load import measure_tenants

    return measure_tenants()


def _bench_chaos():
    """Serving fault tolerance (tpudl.serve migration + chaos via
    benchmarks/serve_load.py --chaos): p99 latency of draining a
    loaded replica (page-granular KV migration makes it ~payload
    transfer, asserted < 10% of the longest in-flight generation) and
    the median client-visible token gap across a mid-decode replica
    preemption (zero re-prefill, generate()-parity asserted inside the
    benchmark). Banked from r08 onward (lower is better for both)."""
    from benchmarks.serve_load import measure_chaos

    return measure_chaos()


def _bench_requestlog():
    """Durable request-log tier (tpudl.obs.requestlog via
    benchmarks/serve_load.py): p99 TTFT with logging on vs off under
    the closed-loop serve mix (the never-blocks-the-decode-loop claim,
    measured) and on-disk bytes per logged request, with the
    rotation + per-tenant reconciliation round-trip asserted on the
    way. Banked from r16 onward (lower is better for both)."""
    from benchmarks.serve_load import measure_requestlog

    return measure_requestlog()


def _bench_flywheel():
    """Data-flywheel tier (tpudl.flywheel via benchmarks/
    serve_load.py): the steady-state refresh latency — one
    ``FlywheelController.poll()`` wall time (log flush -> filter ->
    LoRA train -> safe hot-swap) with the train step pre-compiled —
    and the ingestion tax: serving p99 TTFT with sample capture + the
    durable log on over the same closed-loop mix with them off. The
    serve -> refresh -> swap cycle is asserted end-to-end inside the
    benchmark. Banked from r18 onward (lower is better for both)."""
    from benchmarks.serve_load import measure_flywheel

    return measure_flywheel()


def _bench_ft():
    """Fault-tolerance costs (benchmarks/ft_recovery.py): the async
    checkpoint's on-step stall and the kill-to-first-post-restart-step
    recovery time — the two numbers a preemptible-capacity run budget
    is built from."""
    from benchmarks.ft_recovery import measure_ft

    return measure_ft()


def _bench_train_precision():
    """Mixed-precision TRAINING tier (tpudl.train.precision +
    tpudl.ops.fp8_dot via benchmarks/train_precision.py): every
    precision cell loss-parity gated against the f32 control on a
    fixed-seed run (the assertion lives in the benchmark), the fp8
    cell's weight+activation bytes-moved ratio (the speedup ceiling;
    >= 2x asserted, model says ~4x), and the passed-cell count —
    the training-side mirror of the serving parity grid."""
    from benchmarks.train_precision import run_precision_sweep
    from tpudl.ops.attention import is_tpu_backend

    sweep = run_precision_sweep(smoke=not is_tpu_backend())
    return {
        "train_precision_parity_cells": sweep["parity_cells_passed"],
        "train_precision_parity_cells_total": sweep[
            "parity_cells_total"
        ],
        "train_fp8_bytes_ratio": sweep.get(
            "fp8_weight_act_bytes_ratio"
        ),
    }


def _regression_gate(result: dict, strict: bool) -> int:
    """Advisory noise-aware regression check of this run against the
    banked BENCH_r*.json history (scripts/bench_regress.py — the
    median-of-bank protocol BASELINE.md derived from the r05 false
    alarm). Prints the per-metric table to stderr; only ``--strict``
    turns a regression into a nonzero exit, so the driver's JSON line
    always lands."""
    import sys

    try:
        from scripts.bench_regress import (
            default_history_paths,
            format_rows,
            gate,
            normalize_round,
        )

        rows = gate(normalize_round(result), default_history_paths())
    except Exception:
        import traceback

        print("bench_regress gate failed:", file=sys.stderr)
        traceback.print_exc()
        # Under --strict an inoperative gate IS a failure — a CI job
        # whose purpose is gating must not go green with the gate
        # crashed. Advisory mode still reports the JSON line and moves
        # on.
        return 2 if strict else 0
    print(format_rows(rows), file=sys.stderr)
    regressions = [r["metric"] for r in rows if r["status"] == "regression"]
    if regressions:
        print(f"REGRESSION vs banked history: {', '.join(regressions)}",
              file=sys.stderr)
        return 1 if strict else 0
    return 0


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when a metric regresses beyond "
                    "its noise band vs the banked BENCH_r*.json history")
    args = ap.parse_args(argv)

    bert_sps, bert_mfu, bert_fused = _bench_bert()
    try:
        # Fused-epilogue variant (BertConfig.fused_ops=True +
        # loss_impl="auto"): the ROADMAP item-1 measured variant, lean
        # step counts. scripts/bench_regress.py picks the new keys up
        # from r06 onward automatically.
        fo_sps, fo_mfu, _ = _bench_bert(
            fused_ops=True, warmup=10, measure=20
        )
    except Exception:
        import sys
        import traceback

        print("fused-ops bench variant failed:", file=sys.stderr)
        traceback.print_exc()
        fo_sps = fo_mfu = None
    try:
        # Mixed-precision training variant (tpudl.train.precision
        # "bf16" policy: rule-cast bf16 compute, f32 masters, f32
        # reductions) — the ROADMAP item-6 training half, lean step
        # counts like the fused-ops variant.
        bf16_sps, bf16_mfu, _ = _bench_bert(
            precision="bf16", warmup=10, measure=20
        )
    except Exception:
        import sys
        import traceback

        print("bf16-precision bench variant failed:", file=sys.stderr)
        traceback.print_exc()
        bf16_sps = bf16_mfu = None
    resnet_ips = _bench_resnet()
    resnet50_ips = _bench_resnet50()
    bl_sps, bl_mfu, bl_mfu_compiled = _bench_bert_large()
    try:
        pipe_legacy, pipe_new = _bench_input_pipeline()
    except Exception:
        # The model metrics above must still report, but a silently-null
        # feeding-rate field would hide a broken benchmark — leave the
        # evidence on stderr.
        import sys
        import traceback

        print("input-pipeline bench failed:", file=sys.stderr)
        traceback.print_exc()
        pipe_legacy = pipe_new = None
    try:
        serve = _bench_serve()
    except Exception:
        import sys
        import traceback

        print("serve bench failed:", file=sys.stderr)
        traceback.print_exc()
        serve = {}
    try:
        serve_replicas = _bench_serve_replicas()
    except Exception:
        import sys
        import traceback

        print("serve replica bench failed:", file=sys.stderr)
        traceback.print_exc()
        serve_replicas = {}
    try:
        fleet = _bench_fleet()
    except Exception:
        import sys
        import traceback

        print("fleet autoscale bench failed:", file=sys.stderr)
        traceback.print_exc()
        fleet = {}
    try:
        tenants = _bench_tenants()
    except Exception:
        import sys
        import traceback

        print("multi-tenant bench failed:", file=sys.stderr)
        traceback.print_exc()
        tenants = {}
    try:
        chaos_tier = _bench_chaos()
    except Exception:
        import sys
        import traceback

        print("serve chaos bench failed:", file=sys.stderr)
        traceback.print_exc()
        chaos_tier = {}
    try:
        rlog = _bench_requestlog()
    except Exception:
        import sys
        import traceback

        print("request-log bench failed:", file=sys.stderr)
        traceback.print_exc()
        rlog = {}
    try:
        flywheel = _bench_flywheel()
    except Exception:
        import sys
        import traceback

        print("flywheel bench failed:", file=sys.stderr)
        traceback.print_exc()
        flywheel = {}
    try:
        ft = _bench_ft()
    except Exception:
        import sys
        import traceback

        print("fault-tolerance bench failed:", file=sys.stderr)
        traceback.print_exc()
        ft = {}
    try:
        fleet_mesh = _bench_fleet_mesh()
    except Exception:
        import sys
        import traceback

        print("fleet mesh bench failed:", file=sys.stderr)
        traceback.print_exc()
        fleet_mesh = {}
    try:
        parity_grid = _bench_parity_grid()
    except Exception:
        import sys
        import traceback

        print("parity-grid bench failed:", file=sys.stderr)
        traceback.print_exc()
        parity_grid = {}
    try:
        prefix_spec = _bench_prefix_spec()
    except Exception:
        import sys
        import traceback

        print("prefix/spec bench failed:", file=sys.stderr)
        traceback.print_exc()
        prefix_spec = {}
    try:
        block_pins = _bench_block_pins()
    except Exception:
        import sys
        import traceback

        print("block-pin sweep failed:", file=sys.stderr)
        traceback.print_exc()
        block_pins = {}
    try:
        train_prec = _bench_train_precision()
    except Exception:
        import sys
        import traceback

        print("train-precision bench failed:", file=sys.stderr)
        traceback.print_exc()
        train_prec = {}

    vs_baseline = (
        bert_sps / BASELINE_BERT_SAMPLES_PER_SEC
        if BASELINE_BERT_SAMPLES_PER_SEC
        else 1.0
    )
    result = {
        "metric": "bert_base_sst2_train_throughput",
        "value": round(bert_sps, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
        "mfu": round(bert_mfu, 4),
        "bert_batch": BERT_BATCH,
        # Fused K-step dispatch (steps_per_dispatch=8) vs the
        # single-dispatch headline above: per-step wall-time
        # delta and ratio (benchmarks/dispatch_overhead.py has
        # the width sweep). The headline path stays
        # single-dispatch — the fused path is opt-in.
        "step_dispatch_overhead_ms": bert_fused.get(
            "step_dispatch_overhead_ms"
        ),
        "fused_dispatch_speedup": bert_fused.get(
            "fused_dispatch_speedup"
        ),
        # Fused-epilogue kernel tier (tpudl/ops norms/mlp_fused/
        # cross_entropy behind BertConfig.fused_ops + loss_impl):
        # the same BERT-base workload with the Pallas epilogue
        # kernels on — the ROADMAP item-1 attack (target MFU
        # >= 0.65), measured as a variant until it earns the
        # default. benchmarks/fused_epilogue.py has the
        # per-kernel decomposition.
        "bert_base_mfu_fused_ops": round(fo_mfu, 4)
        if fo_mfu is not None
        else None,
        "bert_base_fused_ops_samples_per_sec": round(fo_sps, 1)
        if fo_sps is not None
        else None,
        # Mixed-precision training tier (tpudl.train.precision +
        # tpudl.ops.fp8_dot via benchmarks/train_precision.py): the
        # bf16-policy BERT-base MFU variant, the fp8 cell's
        # weight+activation bytes-moved ratio vs f32 (the speedup
        # ceiling — the bytes model says ~4x, >= 2x asserted in the
        # benchmark), and the loss-parity cell count (every cell
        # gated against the fixed-seed f32 control inside the
        # benchmark; a failed gate raises there, so a banked count
        # means every band held).
        "bert_base_mfu_bf16": round(bf16_mfu, 4)
        if bf16_mfu is not None
        else None,
        "bert_base_bf16_samples_per_sec": round(bf16_sps, 1)
        if bf16_sps is not None
        else None,
        "train_fp8_bytes_ratio": train_prec.get(
            "train_fp8_bytes_ratio"
        ),
        "train_precision_parity_cells": train_prec.get(
            "train_precision_parity_cells"
        ),
        "resnet50_imagenet_images_per_sec_chip": round(resnet50_ips, 1),
        "resnet50_vs_baseline": round(
            resnet50_ips / BASELINE_RESNET50_IMAGES_PER_SEC, 3
        )
        if BASELINE_RESNET50_IMAGES_PER_SEC
        else 1.0,
        "resnet18_images_per_sec_chip_best_of_windows": round(
            resnet_ips, 1
        ),
        # Ratio base corrected round 6: median (not max) of the
        # banked same-protocol best-of-4-windows runs, so both
        # sides are single draws — see BASELINE.md (the r05
        # 0.923 was the max-of-4 denominator bias, not a
        # regression).
        "resnet18_vs_baseline_like_protocol": round(
            resnet_ips / BASELINE_RESNET_IMAGES_PER_SEC_BEST, 3
        ),
        # configs[3] building block at its DECLARED batch 256 via
        # 4x64 accumulation (round 4; r3 banked 356 samples/s,
        # 46.5% MFU at batch 64 monolithic).
        "bert_large_samples_per_sec_chip": round(bl_sps, 1),
        "bert_large_mfu_6nd": round(bl_mfu, 4),
        # Compiled-cost basis (the honest one — see BASELINE.md
        # round-5 row): live AOT cost_analysis x accum, None if
        # the counted-once ratio guard tripped.
        "bert_large_mfu_compiled": round(bl_mfu_compiled, 4)
        if bl_mfu_compiled is not None
        else None,
        # Host feeding rate (model-free, benchmarks/
        # input_pipeline.py): uint8-wire two-stage pipeline, with
        # the pre-overhaul f32 single-worker feed as its ratio
        # base — the perf trajectory of the INPUT path.
        "input_pipeline_images_per_sec_host": round(pipe_new, 1)
        if pipe_new is not None
        else None,
        "input_pipeline_vs_legacy_feed": round(
            pipe_new / pipe_legacy, 3
        )
        if pipe_new is not None and pipe_legacy
        else None,
        # Serving engine (tpudl.serve via benchmarks/
        # serve_load.py): continuous-batching throughput, tail
        # TTFT, and the continuous-vs-static speedup at equal
        # slot count on the ragged request mix.
        "serve_tokens_per_sec": serve.get("serve_tokens_per_sec"),
        "serve_p99_ttft_ms": serve.get("serve_p99_ttft_ms"),
        "serve_vs_static_batching": serve.get(
            "serve_vs_static_batching"
        ),
        # Dispatch hygiene (tpudl.analysis wired into serve_load's
        # timed window): backend compiles observed during the decode
        # steady state. Expected 0; bench_regress gates this
        # zero-tolerance (any positive draw is a regression — a
        # shape/dtype/static arg quietly varying per step).
        "serve_steady_state_recompiles": serve.get(
            "serve_steady_state_recompiles"
        ),
        # Multi-replica router tier (tpudl.serve.router): routed
        # 2-replica throughput, scaling efficiency vs 2x one
        # replica, and the int8 paged KV cache's resident slots
        # per GB (the capacity lever paging + quantization buy).
        "serve_tokens_per_sec_2rep": serve_replicas.get(
            "serve_tokens_per_sec_2rep"
        ),
        "serve_scaling_efficiency": serve_replicas.get(
            "serve_scaling_efficiency"
        ),
        "serve_kv_slots_per_gb": serve_replicas.get(
            "serve_kv_slots_per_gb"
        ),
        # Fleet observability + autoscaling tier (tpudl.obs.fleet +
        # tpudl.serve.autoscale via benchmarks/serve_load.py): how
        # long the SLO-driven control loop takes from scale-up to
        # burn-clear under 2x overload, and the FleetMonitor's
        # per-cycle HTTP scrape cost over live exporters.
        "autoscale_recovery_s": fleet.get("autoscale_recovery_s"),
        "fleet_scrape_overhead_ms": fleet.get(
            "fleet_scrape_overhead_ms"
        ),
        # Multi-tenant LoRA serving (tpudl.serve.lora adapter pool +
        # the segmented-LoRA kernel via benchmarks/serve_load.py
        # --tenants): resident adapters per GB of pool, batched
        # heterogeneous decode throughput at 64 resident adapters
        # (>= 2x sequential per-tenant dispatch asserted in the
        # benchmark), and the victims' p99 TTFT ratio under one
        # tenant's 4x overload (quota isolation, <= 1.3x asserted).
        "serve_adapters_per_gb": tenants.get("serve_adapters_per_gb"),
        "serve_tokens_per_sec_64adapters": tenants.get(
            "serve_tokens_per_sec_64adapters"
        ),
        "serve_tenant_isolation_p99_ratio": tenants.get(
            "serve_tenant_isolation_p99_ratio"
        ),
        # Serving fault tolerance (tpudl.serve KV migration + chaos
        # harness via benchmarks/serve_load.py --chaos): p99 drain of
        # a loaded replica (migration-based — ~transfer time, not the
        # longest generation) and the median failover token gap a
        # client sees across a mid-decode preemption.
        "serve_drain_p99_ms": chaos_tier.get("serve_drain_p99_ms"),
        "failover_token_gap_ms": chaos_tier.get(
            "failover_token_gap_ms"
        ),
        # Durable request log (tpudl.obs.requestlog via benchmarks/
        # serve_load.py): p99 TTFT with the log enabled over the same
        # closed-loop mix with it disabled (the bounded-queue writer's
        # never-blocks-the-decode-loop claim, measured), and on-disk
        # bytes per logged request (rotation + per-tenant token
        # reconciliation asserted inside the benchmark).
        "requestlog_overhead_p99_ttft_ratio": rlog.get(
            "requestlog_overhead_p99_ttft_ratio"
        ),
        "requestlog_bytes_per_request": rlog.get(
            "requestlog_bytes_per_request"
        ),
        # Data flywheel (tpudl.flywheel via benchmarks/serve_load.py):
        # the steady-state refresh lag — one controller poll's wall
        # time from record threshold to refreshed factors swapped in
        # (train step pre-compiled) — and the ingestion tax, serving
        # p99 TTFT with sample capture + the durable log on vs off
        # over the same closed-loop mix (the serve -> refresh -> swap
        # cycle asserted inside the benchmark).
        "flywheel_refresh_latency_s": flywheel.get(
            "flywheel_refresh_latency_s"
        ),
        "flywheel_serving_p99_impact_ratio": flywheel.get(
            "flywheel_serving_p99_impact_ratio"
        ),
        # Pod-real fleet tier (tpudl.fleet via benchmarks/
        # fleet_mesh.py, subprocess): elastic reshard-restore wall
        # time for a 4-device checkpoint onto an 8-device mesh (the
        # payload MB rides for the bytes model), routed tokens/sec
        # over two 4-device tensor-parallel MeshReplicas, and the
        # chip mover's burn-to-cleared time across the full
        # preempt -> shrink -> serve -> drain -> grow scenario
        # (zero dropped results asserted inside the benchmark).
        "fleet_reshard_restore_s": fleet_mesh.get(
            "fleet_reshard_restore_s"
        ),
        "fleet_reshard_payload_mb": fleet_mesh.get(
            "fleet_reshard_payload_mb"
        ),
        "serve_tokens_per_sec_2mesh": fleet_mesh.get(
            "serve_tokens_per_sec_2mesh"
        ),
        "chipmover_burn_cleared_s": fleet_mesh.get(
            "chipmover_burn_cleared_s"
        ),
        # Fault tolerance (tpudl.ft via benchmarks/
        # ft_recovery.py): the async checkpoint's mean on-step
        # stall (vs the synchronous save of the same payload)
        # and the kill-to-first-post-restart-step recovery
        # time.
        "checkpoint_step_stall_ms": round(
            ft["checkpoint_step_stall_ms"], 2
        )
        if "checkpoint_step_stall_ms" in ft
        else None,
        "checkpoint_sync_save_ms": round(
            ft["checkpoint_sync_save_ms"], 2
        )
        if "checkpoint_sync_save_ms" in ft
        else None,
        "recovery_time_sec": round(ft["recovery_time_sec"], 3)
        if "recovery_time_sec" in ft
        else None,
        # Low-precision serving grid (tpudl.quant via benchmarks/
        # parity_grid.py): simulated-device TPOT of the int8-weights
        # cell, the stored-bytes ratio on its quantized layers
        # (>= 3.5x asserted in the benchmark), and how many
        # precision x backend cells passed their parity gate.
        "serve_tpot_int8_weights_ms": parity_grid.get(
            "serve_tpot_int8_weights_ms"
        ),
        "quant_weight_bytes_ratio": parity_grid.get(
            "quant_weight_bytes_ratio"
        ),
        "parity_grid_cells_passed": parity_grid.get(
            "parity_grid_cells_passed"
        ),
        # Prefix-sharing + speculative decoding (tpudl.serve radix
        # cache + speculate via benchmarks/serve_load.py): p50 TTFT on
        # the 50%-shared-prefix mix with sharing on (the benchmark
        # asserts >= 2x vs no-sharing), per-stream accepted tokens per
        # speculative window (>= 2 asserted), and speculative
        # tokens/sec on the simulated device (beats the plain paged
        # baseline, asserted).
        "serve_ttft_shared_prefix_ms": prefix_spec.get(
            "serve_ttft_shared_prefix_ms"
        ),
        "spec_accepted_tokens_per_step": prefix_spec.get(
            "spec_accepted_tokens_per_step"
        ),
        "serve_tokens_per_sec_spec": prefix_spec.get(
            "serve_tokens_per_sec_spec"
        ),
        # JSON tail: the fused-epilogue block-size sweep's winning
        # pins (benchmarks/fused_epilogue.py --sweep-blocks) — the
        # evidence a TPU round uses to flip fused defaults. Non-numeric
        # on purpose; the regression gate skips them.
        "fused_block_pins": block_pins.get("pins"),
        "fused_block_pin_cmd": block_pins.get("command"),
    }
    print(json.dumps(result))
    return _regression_gate(result, strict=args.strict)


if __name__ == "__main__":
    import sys

    sys.exit(main())
