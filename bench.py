"""Driver benchmark: one JSON line with the headline metric.

Measures steady-state training throughput of the BASELINE.json configs[0]
workload (ResNet-18 / CIFAR-10-shaped data) on the real device. The
reference publishes no numbers (BASELINE.md — `"published": {}`), so
``vs_baseline`` is reported against the first value this repo banked in
BASELINE.md (images/sec on 1x TPU v5 lite); until one exists it is 1.0.

Timing protocol (see .claude/skills/verify/SKILL.md): the remote-TPU relay
makes `block_until_ready` unreliable for timing, so every window is closed
by a scalar host readback, and a long warmup burst absorbs relay buffering.
"""

import json
import time

import jax
import jax.numpy as jnp
import optax

# Value banked in BASELINE.md for this metric (images/sec, 1x TPU v5 lite).
BASELINE_IMAGES_PER_SEC = 29000.0

BATCH = 256
WARMUP_STEPS = 25
MEASURE_STEPS = 50


def main():
    from tpudl.data.synthetic import synthetic_classification_batches
    from tpudl.models import ResNet18
    from tpudl.runtime import MeshSpec, make_mesh
    from tpudl.train import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    model = ResNet18(num_classes=10, small_inputs=True)
    state = create_train_state(
        jax.random.key(0),
        model,
        jnp.zeros((1, 32, 32, 3)),
        optax.sgd(0.1, momentum=0.9),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(make_classification_train_step(), mesh, state, None)

    batch = next(
        synthetic_classification_batches(BATCH, image_shape=(32, 32, 3), num_classes=10)
    )
    batch = jax.device_put(batch)
    rng = jax.random.key(1)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # close the warmup window with a readback

    start = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start

    images_per_sec = BATCH * MEASURE_STEPS / elapsed / jax.device_count()
    print(
        json.dumps(
            {
                "metric": "resnet18_cifar10_train_throughput",
                "value": round(images_per_sec, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
